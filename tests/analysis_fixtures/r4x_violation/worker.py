"""Dirty twin: the thread entry and an imported-state mutation."""

import threading

from .state import EVENTS, Stream


class Prefetcher:
    def __init__(self):
        self.stream = Stream()
        self._thread = threading.Thread(target=self._work, daemon=True)

    def _work(self):
        while True:
            item = self._produce()
            if item is None:
                return

    def _produce(self):
        chunk = self.stream.next_chunk()
        EVENTS.append(len(chunk))  # R4x: state imported from .state, no lock
        return chunk
