"""Resources acquired without an all-paths release: straight-line
close/join (an exception between acquire and release leaks), a
fire-and-forget constructor, a self-stored server no method tears
down, and an acknowledged deliberate leak."""
import socket
import threading

from http.server import HTTPServer


def leaky_probe(host):
    s = socket.socket()
    s.connect((host, 80))
    s.send(b"ping")
    s.close()


def leaky_workers(n):
    ts = [threading.Thread(target=print) for _ in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def fire_and_forget():
    threading.Thread(target=print).start()


class Holder:
    def open_server(self):
        self.srv = HTTPServer(("", 0), None)


def acked_probe(host):
    s = socket.socket()  # jaxlint: ignore[R15] demo deliberate leak: process-lifetime probe socket
    s.connect((host, 80))
