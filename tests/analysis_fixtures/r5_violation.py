# jaxlint R5 fixture: swallowed exceptions.  Read as text — never imported.


def probe_backend():
    try:
        import does_not_exist  # noqa: F401

        return True
    except Exception:  # line 9: swallows everything silently
        return False


def best_effort_cleanup(path):
    import os

    try:
        os.unlink(path)
    except:  # line 18: bare except, nothing logged
        pass
