"""Dirty twin: jitted kernels with static args, defined HERE, abused in
driver.py (cross-module static-arg tracking the per-file R1 misses)."""

import functools

import jax


@functools.partial(jax.jit, static_argnames=("n",))
def compute(x, n):
    return x * n


def plain(x, n):
    return x + n


# Module-scope jit wrapper: the alias is the jitted callable.
fast_plain = jax.jit(plain, static_argnames=("n",))
