"""Dirty twin: call sites of imported jitted kernels."""

from .kernels import compute, fast_plain


def run(xs):
    out = []
    for i in range(8):
        out.append(compute(xs, n=i))  # R1x: loop-varying static arg
    out.append(compute(xs, n=[1, 2]))  # R1x: unhashable static arg
    for j in range(4):
        out.append(fast_plain(xs, n=j))  # R1x: via module-scope jit alias
    return out
