# jaxlint R1 clean twin: same shapes, no recompilation hazard.
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("chunk",))
def sweep(x, chunk):
    return x[:chunk].sum()


@functools.partial(jax.jit, static_argnums=(1,))
def scaled(x, factor):
    return x * factor


def fixed_static_in_loop(x, chunk=64):
    total = 0.0
    for _ in range(100):
        total += sweep(x, chunk)  # static arg constant across iterations
    return total


def hashable_static(x):
    return scaled(x, (2, 3))  # tuple is hashable: one compile


def jit_hoisted(fns, x):
    jitted = [jax.jit(f) for f in fns]

    def run_all():
        return [jf(x) for jf in jitted]

    return run_all()
