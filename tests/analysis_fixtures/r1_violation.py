# jaxlint R1 fixture: recompilation hazards.  Read by tests as text —
# never imported or executed.
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("chunk",))
def sweep(x, chunk):
    return x[:chunk].sum()


@functools.partial(jax.jit, static_argnums=(1,))
def scaled(x, factor):
    return x * factor


def varying_static_in_loop(x):
    total = 0.0
    for step in range(100):
        total += sweep(x, step)  # line 22: static 'chunk' varies per iteration
    return total


def unhashable_static(x):
    return scaled(x, [2, 3])  # line 27: list literal as static arg


def jit_in_loop(fns, x):
    outs = []
    for f in fns:
        jf = jax.jit(f)  # line 33: fresh jit wrapper per iteration
        outs.append(jf(x))
    return outs
