# jaxlint R6 fixture: direct stats-dict mutation.  Read as text — never
# imported.


def count_dispatch(ctx):
    ctx.stats["device_dispatches"] += 1  # line 6: augmented assignment


def reset_counter(ctx, before):
    ctx.stats["lut7_candidates"] = before  # line 10: subscript assignment


def bump_param(stats, key):
    stats[key] = stats.get(key, 0) + 1  # line 14: bare stats param poke


def seed_counters(rdv):
    rdv.stats.update(submits=0, dispatches=0)  # line 18: mutating call


def drop_counter(ctx):
    ctx.stats.pop("warm_hits", None)  # line 22: mutating call


def poke_nested(ctx, phase):
    ctx.stats["device_wait_s"][phase] = 0.0  # line 26: nested subscript
