"""The deterministic twins: same sinks, deterministic inputs."""

import os


def manifest(directory):
    names = sorted(os.listdir(directory))
    return canonicalize(names)


def canonicalize(parts):
    return "|".join(parts)


def derive_key(seed):
    import numpy as np

    return np.random.default_rng(seed)


def fan_out(journal, items):
    for item in sorted(set(items)):
        journal.append("item", name=item)
