"""Resilience subsystem tests: durable checkpoints, fault injection,
torn-file recovery, journal mechanics, and the ChunkPrefetcher
double-fault contract.

The crash-action tests run a tiny no-jax subprocess (sboxgates_tpu's
package init is import-light), so a real ``os._exit`` mid-write proves
the on-disk guarantee: the complete old file or the complete new file,
never a torn checkpoint.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.graph.state import GATES, State
from sboxgates_tpu.graph.xmlio import (
    StateLoadError,
    load_state,
    save_state,
    state_filename,
    state_to_xml,
)
from sboxgates_tpu.resilience import faults
from sboxgates_tpu.resilience.checkpoint import (
    TMP_PREFIX,
    clean_stale_tmp,
    latest_valid_state,
    with_digest,
)
from sboxgates_tpu.resilience.faults import InjectedFault

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.disarm()
    yield
    faults.disarm()


def small_state(n_extra=2, seed=0):
    rng = np.random.default_rng(seed)
    st = State.init_inputs(3)
    for _ in range(n_extra):
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    st.outputs[0] = st.num_gates - 1
    return st


# -- fault-injection registry ---------------------------------------------


def test_fault_spec_parsing():
    specs = faults.parse_spec("a.b:raise@3,c.d:hang@2+, e.f:crash ")
    assert specs["a.b"].action == "raise" and specs["a.b"].first == 3
    assert specs["a.b"].once
    assert specs["c.d"].action == "hang" and not specs["c.d"].once
    assert specs["e.f"].action == "crash" and specs["e.f"].first == 1
    for bad in ("x", "a:nosuch", "a:raise@0", "a:raise@x", "a:raise:b"):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)


def test_fault_point_once_vs_onward():
    faults.arm("t.once", "raise", "2")
    faults.fault_point("t.once")  # hit 1: silent
    with pytest.raises(InjectedFault):
        faults.fault_point("t.once")  # hit 2: fires
    faults.fault_point("t.once")  # hit 3: silent again (once)
    faults.arm("t.onward", "raise", "2+")
    faults.fault_point("t.onward")
    for _ in range(3):
        with pytest.raises(InjectedFault):
            faults.fault_point("t.onward")
    assert faults.hit_count("t.onward") == 4


def test_unarmed_fault_point_is_free():
    faults.fault_point("never.armed")  # no spec: no-op, no error


# -- durable checkpoint writes --------------------------------------------


def test_save_state_writes_digest_and_roundtrips(tmp_path):
    st = small_state()
    path = save_state(st, str(tmp_path))
    raw = open(path).read()
    assert "sbg:sha256=" in raw
    st2 = load_state(path)
    assert state_to_xml(st2) == state_to_xml(st)
    assert not [
        f for f in os.listdir(tmp_path) if f.startswith(TMP_PREFIX)
    ], "temp file leaked"
    # The atomic write must publish umask-governed permissions, not
    # mkstemp's 0600 (peers and the reference tool read these files).
    umask = os.umask(0)
    os.umask(umask)
    assert os.stat(path).st_mode & 0o777 == 0o666 & ~umask


def test_load_state_rejects_torn_and_corrupt(tmp_path):
    st = small_state()
    path = save_state(st, str(tmp_path))
    raw = open(path).read()
    # corrupted body under a recorded digest
    open(path, "w").write(raw.replace('type="XOR"', 'type="AND"'))
    with pytest.raises(StateLoadError):
        load_state(path)
    # truncated mid-file (digest comment gone entirely)
    open(path, "w").write(raw[: len(raw) // 2])
    with pytest.raises(StateLoadError):
        load_state(path)


def test_reference_format_files_still_load(tmp_path):
    # A digest-less file (what the reference binary writes) passes the
    # structural validation unchanged.
    st = small_state()
    p = tmp_path / "ref.xml"
    p.write_text(state_to_xml(st))
    st2 = load_state(str(p))
    assert state_to_xml(st2) == state_to_xml(st)


def _crash_script(site: str) -> str:
    return textwrap.dedent(
        f"""
        import sys
        sys.path.insert(0, {ROOT!r})
        import numpy as np
        from sboxgates_tpu.core import boolfunc as bf
        from sboxgates_tpu.graph.state import GATES, State
        from sboxgates_tpu.graph.xmlio import save_state

        rng = np.random.default_rng(0)
        st = State.init_inputs(3)
        for _ in range(2):
            a, b = rng.choice(st.num_gates, size=2, replace=False)
            st.add_gate(bf.XOR, int(a), int(b), GATES)
        st.outputs[0] = st.num_gates - 1
        save_state(st, sys.argv[1])          # first write: completes
        st.outputs[1] = st.num_gates - 2     # new content, same round-trip
        save_state(st, sys.argv[1])          # second write: dies mid-way
        """
    )


@pytest.mark.parametrize("site", ["ckpt.write", "ckpt.replace"])
def test_crash_during_save_never_tears_a_checkpoint(tmp_path, site):
    """Acceptance: a crash at any registered fault site during save_state
    leaves either the complete old file or the complete new file —
    digest-verified — and latest_valid_state recovers the newest intact
    one."""
    proc = subprocess.run(
        [sys.executable, "-c", _crash_script(site), str(tmp_path)],
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "SBG_FAULTS": f"{site}:crash@2"},
    )
    assert proc.returncode == faults.CRASH_EXIT_CODE, proc.stderr
    # Every surviving .xml is complete and digest-valid.
    xmls = [f for f in os.listdir(tmp_path) if f.endswith(".xml")]
    assert xmls, "first checkpoint vanished"
    for f in xmls:
        load_state(str(tmp_path / f))  # raises on a torn file
    got = latest_valid_state(str(tmp_path))
    assert got is not None
    # The second write died before (or during) publication: the first
    # checkpoint is the newest intact state.
    _, st = got
    assert st.outputs[1] == 0xFFFF  # NO_GATE: new content never landed
    # A crash mid-write strands a temp file; resume-time cleanup removes
    # it (and only it).
    stranded = [f for f in os.listdir(tmp_path) if f.startswith(TMP_PREFIX)]
    if site == "ckpt.write":
        assert stranded
    removed = clean_stale_tmp(str(tmp_path))
    assert removed == len(stranded)
    assert not [
        f for f in os.listdir(tmp_path) if f.startswith(TMP_PREFIX)
    ]


def test_latest_valid_state_skips_corrupt_newest(tmp_path):
    st = small_state()
    good = save_state(st, str(tmp_path))
    bad = tmp_path / "9-999-9999-0-deadbeef.xml"
    bad.write_text(with_digest(state_to_xml(st))[:40])  # torn
    os.utime(good, (1, 1))  # make the torn file strictly newest
    path, recovered = latest_valid_state(str(tmp_path))
    assert path == good
    assert state_to_xml(recovered) == state_to_xml(st)


def test_latest_valid_state_mtime_ties_break_by_name(tmp_path):
    """Recovery ordering is (mtime, path) over a SORTED directory scan:
    checkpoints with identical mtimes resolve to the lexicographically
    greatest name on every platform — the scan must not ride raw
    os.listdir enumeration order (regression for the unsorted scan R11
    flagged)."""
    st = small_state()
    for name in ("b-tie.xml", "a-tie.xml", "c-tie.xml"):
        p = tmp_path / name
        p.write_text(with_digest(state_to_xml(st)))
        os.utime(p, (5, 5))
    for _ in range(3):
        path, recovered = latest_valid_state(str(tmp_path))
        assert path == str(tmp_path / "c-tie.xml")
        assert state_to_xml(recovered) == state_to_xml(st)


def test_clean_stale_tmp_removes_in_sorted_order(tmp_path, monkeypatch):
    """Stranded-temp removal visits a sorted listing, so the removal
    sequence (and therefore which files survive a mid-sweep OSError)
    is identical on every filesystem."""
    order = []
    real_unlink = os.unlink

    def recording_unlink(p):
        order.append(os.path.basename(p))
        real_unlink(p)

    monkeypatch.setattr(os, "unlink", recording_unlink)
    for name in ("zz", "aa", "mm"):
        (tmp_path / f"{TMP_PREFIX}{name}.tmp").write_text("x")
    (tmp_path / "keep.xml").write_text("x")
    assert clean_stale_tmp(str(tmp_path)) == 3
    assert order == sorted(order) and len(order) == 3
    assert os.listdir(str(tmp_path)) == ["keep.xml"]


def test_latest_valid_state_empty_dir(tmp_path):
    assert latest_valid_state(str(tmp_path)) is None


# -- journal mechanics -----------------------------------------------------


def test_journal_append_snapshot_and_torn_tail(tmp_path):
    from sboxgates_tpu.resilience.journal import (
        JOURNAL_NAME,
        SearchJournal,
    )

    j = SearchJournal.start(str(tmp_path), config={"seed": 7})
    j.append("round_done", round=1, beam=["a.xml"], rng={"bg": {}, "seed_buf": []})
    j.append("round_done", round=2, beam=["b.xml"], rng={"bg": {}, "seed_buf": []})
    # Simulate a torn tail: a crashed append leaves half a record with no
    # trailing newline.
    with open(tmp_path / JOURNAL_NAME, "a") as f:
        f.write('{"seq": 3, "type": "round_do')
    j2 = SearchJournal.resume(str(tmp_path))
    assert [r["type"] for r in j2.records] == [
        "run_start", "round_done", "round_done",
    ]
    assert j2.last("round_done")["round"] == 2
    assert j2.config == {"seed": 7}
    assert not j2.complete
    # resume() truncated the torn fragment, so post-resume appends never
    # weld onto garbage: a THIRD resume still sees every record.
    j2.append("round_done", round=3, beam=["c.xml"], rng={"bg": {}, "seed_buf": []})
    j3 = SearchJournal.resume(str(tmp_path))
    assert [r.get("round") for r in j3.of_type("round_done")] == [1, 2, 3]
    # The JSONL gone entirely: the atomic snapshot fallback restores a
    # valid PREFIX (it rides run boundaries + every SNAPSHOT_EVERY
    # appends, and resuming from an earlier record just re-runs those
    # units deterministically).
    os.unlink(tmp_path / JOURNAL_NAME)
    j4 = SearchJournal.resume(str(tmp_path))
    assert j4.records[0]["type"] == "run_start"
    assert [r["type"] for r in j4.records] == [
        r["type"] for r in j3.records[: len(j4.records)]
    ]


def test_journal_run_done_snapshots_everything(tmp_path):
    from sboxgates_tpu.resilience.journal import JOURNAL_NAME, SearchJournal

    j = SearchJournal.start(str(tmp_path), config={})
    j.append("round_done", round=1, beam=[], rng={"bg": {}, "seed_buf": []})
    j.append("run_done", beam=[])
    os.unlink(tmp_path / JOURNAL_NAME)
    # run boundaries always refresh the snapshot: nothing lost.
    j2 = SearchJournal.resume(str(tmp_path))
    assert [r["type"] for r in j2.records] == [
        "run_start", "round_done", "run_done",
    ]
    assert j2.complete


def test_journal_start_drops_previous_snapshot(tmp_path):
    """A new run owns the directory: even if it dies before its
    run_start is durable, the OLD run's snapshot must not be silently
    resurrected by the next resume."""
    from sboxgates_tpu.resilience.journal import (
        JOURNAL_NAME,
        SNAPSHOT_NAME,
        JournalError,
        SearchJournal,
    )

    j = SearchJournal.start(str(tmp_path), config={"run": "A"})
    j.append("run_done", beam=[])
    assert os.path.exists(tmp_path / SNAPSHOT_NAME)
    # Run B starts and crashes between the snapshot removal / JSONL
    # truncation and the run_start append: simulate by doing what
    # start() does up to that point.
    os.unlink(tmp_path / SNAPSHOT_NAME)
    open(tmp_path / JOURNAL_NAME, "w").close()
    with pytest.raises(JournalError):
        SearchJournal.resume(str(tmp_path))  # run A must NOT come back


def test_journal_readonly_restores_but_never_writes(tmp_path):
    from sboxgates_tpu.resilience.journal import JOURNAL_NAME, SearchJournal

    j = SearchJournal.start(str(tmp_path), config={"seed": 1})
    j.append("round_done", round=1, beam=[], rng={"bg": {}, "seed_buf": []})
    before = open(tmp_path / JOURNAL_NAME).read()
    ro = SearchJournal.resume(str(tmp_path), readonly=True)
    assert ro.readonly and not ro.writable
    assert ro.last("round_done")["round"] == 1  # restore works
    ro.append("round_done", round=2, beam=[], rng={})  # dropped
    assert open(tmp_path / JOURNAL_NAME).read() == before
    assert ro.last("round_done")["round"] == 1


def test_journal_resume_requires_run_start(tmp_path):
    from sboxgates_tpu.resilience.journal import JournalError, SearchJournal

    with pytest.raises(JournalError):
        SearchJournal.resume(str(tmp_path))


def test_rng_snapshot_restore_exact():
    """The snapshot must capture the seed-buffer tail, not just the
    bit-generator: next_seed() draws in 256-entry batches."""
    from sboxgates_tpu.search import Options, SearchContext

    ctx = SearchContext(Options(seed=42))
    for _ in range(5):
        ctx.next_seed()
    snap = json.loads(json.dumps(ctx.rng_snapshot()))  # JSON round-trip
    expect = [ctx.next_seed() for _ in range(300)]  # crosses a refill
    expect_host = ctx.rng.integers(0, 1 << 31)

    ctx2 = SearchContext(Options(seed=999))  # different seed on purpose
    ctx2.rng_restore(snap)
    got = [ctx2.next_seed() for _ in range(300)]
    assert got == expect
    assert ctx2.rng.integers(0, 1 << 31) == expect_host


def test_journal_seq_check_detects_desync(monkeypatch):
    """Multi-host resume validation: a process whose round counter
    disagrees with the primary's broadcast fails loudly at the host
    barrier (simulated 2-process run via monkeypatched collectives)."""
    import jax
    from jax.experimental import multihost_utils

    from sboxgates_tpu.parallel import distributed as dist

    # Single process: no-op, no collective.
    dist.journal_seq_check(3, 4)

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        multihost_utils,
        "broadcast_one_to_all",
        lambda x: np.asarray([5, 9], dtype=np.int64),
    )
    dist.journal_seq_check(5, 9)  # rounds agree: fine
    dist.journal_seq_check(5, None)  # non-primary (no journal): fine
    with pytest.raises(RuntimeError, match="desync"):
        dist.journal_seq_check(3, 4)


# -- ChunkPrefetcher double-fault contract --------------------------------


class _FailingStream:
    """CombinationStream stand-in whose second chunk raises."""

    def __init__(self):
        self.calls = 0

    def next_chunk(self, chunk):
        self.calls += 1
        if self.calls >= 2:
            raise RuntimeError("producer blew up")
        return np.zeros((chunk, 5), dtype=np.int32)


def test_prefetcher_producer_fault_does_not_mask_consumer_fault():
    """The documented double-fault contract (ops/combinatorics.py): a
    pending producer exception must NOT mask an in-flight consumer
    exception on __exit__/close."""
    from sboxgates_tpu.ops.combinatorics import ChunkPrefetcher

    pf = ChunkPrefetcher(_FailingStream(), 4, depth=2)
    with pytest.raises(ValueError, match="consumer failed"):
        with pf:
            item = pf.get()  # first chunk arrives fine
            assert item is not None
            # Producer has (or will) put its failure in the queue; the
            # consumer now dies of its own, unrelated error.
            raise ValueError("consumer failed")
    assert pf.closed  # __exit__ joined the worker despite the pending exc


def test_prefetcher_producer_fault_surfaces_at_the_failed_chunk():
    from sboxgates_tpu.ops.combinatorics import ChunkPrefetcher

    with ChunkPrefetcher(_FailingStream(), 4, depth=2) as pf:
        assert pf.get() is not None
        with pytest.raises(RuntimeError, match="producer blew up"):
            pf.get()
    assert pf.closed


def test_prefetcher_injected_fault_site():
    """prefetch.produce is a registered site: a raise there surfaces
    through the consumer's get(), in both threaded and inline modes."""
    from sboxgates_tpu.ops.combinatorics import (
        ChunkPrefetcher,
        CombinationStream,
    )

    for depth in (2, 1):
        faults.arm("prefetch.produce", "raise", "2")
        try:
            with ChunkPrefetcher(
                CombinationStream(10, 3), 16, depth=depth
            ) as pf:
                assert pf.get() is not None
                with pytest.raises(InjectedFault):
                    pf.get()
        finally:
            faults.disarm()
