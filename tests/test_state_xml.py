"""Graph state and XML persistence tests."""

import numpy as np
import pytest

from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph import (
    GATES,
    NO_GATE,
    SAT,
    State,
    StateLoadError,
    state_filename,
    state_fingerprint,
    state_from_xml,
    state_to_xml,
)


def build_simple_state():
    """in0 XOR in1, AND with in2; output 0 = the AND."""
    st = State.init_inputs(3)
    x = st.add_gate(bf.XOR, 0, 1, GATES)
    a = st.add_gate(bf.AND, x, 2, GATES)
    st.outputs[0] = a
    return st


def test_init_inputs():
    st = State.init_inputs(6)
    assert st.num_gates == 6
    assert st.num_inputs == 6
    for i in range(6):
        assert np.array_equal(st.table(i), tt.input_table(i))


def test_add_gate_tables():
    st = build_simple_state()
    assert np.array_equal(st.table(3), tt.input_table(0) ^ tt.input_table(1))
    assert np.array_equal(st.table(4), st.table(3) & tt.input_table(2))
    assert st.sat_metric == 12 + 7


def test_add_gate_budget():
    st = State.init_inputs(2)
    st.max_gates = 2
    # num_gates (2) > max_gates (2) is false -> allowed once
    g = st.add_gate(bf.AND, 0, 1, GATES)
    assert g == 2
    g2 = st.add_gate(bf.OR, 0, 1, GATES)
    assert g2 == NO_GATE


def test_add_lut():
    st = State.init_inputs(3)
    g = st.add_lut(0xAC, 0, 1, 2)
    expected = tt.eval_lut(0xAC, tt.input_table(0), tt.input_table(1), tt.input_table(2))
    assert np.array_equal(st.table(g), expected)
    assert st.gates[g].function == 0xAC


def test_copy_independence():
    st = build_simple_state()
    st2 = st.copy()
    st2.add_gate(bf.OR, 0, 1, GATES)
    assert st.num_gates == 5
    assert st2.num_gates == 6
    st2.gates[0].type = bf.LUT
    assert st.gates[0].type == bf.IN


def test_verify_gate():
    st = build_simple_state()
    target = st.table(4).copy()
    st.verify_gate(4, target, tt.mask_table(3))
    with pytest.raises(AssertionError):
        st.verify_gate(3, target, tt.mask_table(3))


def test_xml_roundtrip():
    st = build_simple_state()
    text = state_to_xml(st)
    st2 = state_from_xml(text)
    assert st2.num_gates == st.num_gates
    assert st2.outputs == st.outputs
    for g1, g2 in zip(st.gates, st2.gates):
        assert (g1.type, g1.in1, g1.in2, g1.in3, g1.function) == (
            g2.type,
            g2.in1,
            g2.in2,
            g2.in3,
            g2.function,
        )
    assert np.array_equal(st.live_tables(), st2.live_tables())
    assert st2.sat_metric == st.sat_metric


def test_xml_roundtrip_lut():
    st = State.init_inputs(3)
    g = st.add_lut(0x96, 0, 1, 2)  # 3-input XOR
    st.outputs[1] = g
    st2 = state_from_xml(state_to_xml(st))
    assert st2.gates[3].function == 0x96
    assert np.array_equal(st2.table(3), st.table(3))
    assert st2.sat_metric == 0  # zeroed when LUTs present


def test_xml_exact_text():
    st = build_simple_state()
    expected = (
        '<?xml version="1.0" encoding="UTF-8" ?>\n'
        "<gates>\n"
        '  <output bit="0" gate="4" />\n'
        '  <gate type="IN" />\n'
        '  <gate type="IN" />\n'
        '  <gate type="IN" />\n'
        '  <gate type="XOR">\n'
        '    <input gate="0" />\n'
        '    <input gate="1" />\n'
        "  </gate>\n"
        '  <gate type="AND">\n'
        '    <input gate="3" />\n'
        '    <input gate="2" />\n'
        "  </gate>\n"
        "</gates>\n"
    )
    assert state_to_xml(st) == expected


def test_xml_validation_errors():
    with pytest.raises(StateLoadError):
        state_from_xml("<notgates></notgates>")
    with pytest.raises(StateLoadError):
        state_from_xml('<gates><gate type="BOGUS" /></gates>')
    # forward reference
    with pytest.raises(StateLoadError):
        state_from_xml(
            '<gates><gate type="NOT"><input gate="1" /></gate></gates>'
        )
    # wrong arity
    with pytest.raises(StateLoadError):
        state_from_xml(
            '<gates><gate type="IN" /><gate type="AND">'
            '<input gate="0" /></gate></gates>'
        )
    # function attr on non-LUT
    with pytest.raises(StateLoadError):
        state_from_xml(
            '<gates><gate type="IN" /><gate type="NOT" function="1f">'
            '<input gate="0" /></gate></gates>'
        )
    # more than 8 inputs
    xml = "<gates>" + '<gate type="IN" />' * 9 + "</gates>"
    with pytest.raises(StateLoadError):
        state_from_xml(xml)
    # non-contiguous IN gates
    with pytest.raises(StateLoadError):
        state_from_xml(
            '<gates><gate type="IN" /><gate type="NOT"><input gate="0" /></gate>'
            '<gate type="IN" /></gates>'
        )
    # duplicate output bit
    with pytest.raises(StateLoadError):
        state_from_xml(
            '<gates><output bit="0" gate="0" /><output bit="0" gate="0" />'
            '<gate type="IN" /></gates>'
        )


def test_xsd_contract():
    """Emitted XML must validate against the shipped gates.xsd schema, and
    the schema must reject contract violations (the formal interop
    contract; reference counterpart: gates.xsd)."""
    from sboxgates_tpu.graph.xmlio import validate_xml

    # Well-formed gate and LUT states validate.
    validate_xml(state_to_xml(build_simple_state()))
    st = State.init_inputs(3)
    lut = st.add_lut(0xAC, 0, 1, 2)
    st.outputs[0] = lut
    validate_xml(state_to_xml(st))

    # Schema-level violations are rejected.
    bad_docs = [
        # unknown gate type
        '<gates><output bit="0" gate="0" /><gate type="MAYBE" /></gates>',
        # output bit out of range
        '<gates><output bit="8" gate="0" /><gate type="IN" /></gates>',
        # gate id beyond MAX_GATES
        '<gates><output bit="0" gate="500" /><gate type="IN" /></gates>',
        # four inputs on one gate
        '<gates><output bit="0" gate="0" /><gate type="LUT" function="ac">'
        '<input gate="0" /><input gate="0" /><input gate="0" />'
        '<input gate="0" /></gate></gates>',
        # function attribute not two hex digits
        '<gates><output bit="0" gate="0" /><gate type="LUT" function="xyz">'
        "</gate></gates>",
        # no outputs at all
        '<gates><gate type="IN" /></gates>',
    ]
    for doc in bad_docs:
        with pytest.raises(StateLoadError):
            validate_xml(doc)


def test_fingerprint_stability_and_sensitivity():
    st = build_simple_state()
    fp1 = state_fingerprint(st)
    assert fp1 == state_fingerprint(st)  # deterministic
    st2 = build_simple_state()
    assert state_fingerprint(st2) == fp1  # same structure, same fingerprint
    st2.outputs[0] = 3
    assert state_fingerprint(st2) != fp1


def test_state_filename_format():
    st = build_simple_state()
    name = state_filename(st)
    # 1 output, 2 gates beyond inputs, sat metric 19, output bit 0
    assert name.startswith("1-002-0019-0-")
    assert name.endswith(".xml")
    assert len(name.split("-")) == 5
