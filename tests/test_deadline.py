"""Hung-dispatch deadline tests: DispatchTimeout within the budget,
retry with exponential backoff, and degradation to the host-fallback
path with the same first hit (the acceptance property).  Plus the
replicated degradation protocol for process-spanning meshes: agreed
abort/retry at the verdict barrier, lockstep degrade on exhaustion, and
ZERO verdict round trips on single-host / non-spanning runs."""

import time

import numpy as np
import pytest

from planted import build_planted_lut5_small, verify_lut5_result
from sboxgates_tpu.resilience import faults
from sboxgates_tpu.resilience.deadline import (
    DeadlineConfig,
    DispatchTimeout,
    dispatch_with_retry,
    replicated_dispatch_with_retry,
    run_with_deadline,
    wave_dispatch_with_retry,
)
from sboxgates_tpu.resilience.faults import InjectedFault
from sboxgates_tpu.search import Options, SearchContext
from sboxgates_tpu.search import lut as slut


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.disarm()
    faults.set_rank(None)
    yield
    faults.disarm()
    faults.set_rank(None)


def test_run_with_deadline_passthrough_and_timeout():
    assert run_with_deadline(lambda: 42, 1.0) == 42
    assert run_with_deadline(lambda: 42, 0.0) == 42  # disabled: inline
    t0 = time.monotonic()
    with pytest.raises(DispatchTimeout):
        run_with_deadline(lambda: time.sleep(30), 0.2, label="t")
    assert time.monotonic() - t0 < 5.0  # raised within the budget, not 30s
    with pytest.raises(ZeroDivisionError):  # worker errors propagate
        run_with_deadline(lambda: 1 // 0, 1.0)


def test_dispatch_with_retry_recovers_after_transient_hang():
    """A hang on the FIRST attempt only (a transient stall): one breach,
    one retry, then success — with the re-issue hook invoked."""
    faults.arm("dispatch.sweep", "hang", "1")  # exactly hit 1
    calls = []
    stats = {}
    out = dispatch_with_retry(
        lambda: "ok",
        DeadlineConfig(budget_s=0.2, retries=2, backoff_s=0.01),
        stats=stats,
        on_retry=lambda: calls.append("reissue"),
    )
    assert out == "ok"
    assert stats["deadline_breaches"] == 1
    assert stats["dispatch_retries"] == 1
    assert calls == ["reissue"]


def test_wave_dispatch_exhaustion_attributes_every_lane():
    """The merged-wave guard: ONE window per wave dispatch, breach and
    retry counters per window (not per lane), the re-issue hook fires
    per retry, and the final DispatchTimeout NAMES every lane riding
    the window so per-job failure policy can attribute it."""
    cfg = DeadlineConfig(budget_s=0.02, retries=1, backoff_s=0.01)
    reissues = []
    stats = {}
    with pytest.raises(DispatchTimeout) as ei:
        wave_dispatch_with_retry(
            lambda: time.sleep(5.0), cfg, stats=stats,
            label="fleet[gate_step_stream]", lanes=["jobA", "jobB"],
            on_retry=lambda: reissues.append(1),
        )
    assert "jobA" in str(ei.value) and "jobB" in str(ei.value)
    assert stats["deadline_breaches"] == 2  # one per window, not lane
    assert stats["dispatch_retries"] == 1
    assert len(reissues) == 1


def test_wave_dispatch_recovers_and_inline_when_disabled():
    """A transient hang recovers within the wave's retry schedule, and
    a disabled config short-circuits inline."""
    cfg = DeadlineConfig(budget_s=0.05, retries=2, backoff_s=0.01)
    state = {"calls": 0}

    def resolve():
        state["calls"] += 1
        if state["calls"] == 1:
            time.sleep(5.0)
        return 42

    stats = {}
    assert wave_dispatch_with_retry(
        resolve, cfg, stats=stats, lanes=["j0"],
    ) == 42
    assert stats["deadline_breaches"] == 1
    assert wave_dispatch_with_retry(lambda: 7, None) == 7
    assert wave_dispatch_with_retry(
        lambda: 8, DeadlineConfig(budget_s=0.0)
    ) == 8


def test_dispatch_with_retry_backoff_and_exhaustion():
    faults.arm("dispatch.sweep", "hang")  # every attempt hangs
    stats = {}
    t0 = time.monotonic()
    with pytest.raises(DispatchTimeout):
        dispatch_with_retry(
            lambda: "never",
            DeadlineConfig(budget_s=0.1, retries=2, backoff_s=0.05),
            stats=stats,
        )
    dt = time.monotonic() - t0
    assert stats["deadline_breaches"] == 3  # initial + 2 retries
    assert stats["dispatch_retries"] == 2
    # 3 budgets + backoffs 0.05 + 0.10: the exponential schedule ran.
    assert dt >= 0.1 * 3 + 0.05 + 0.10 - 0.02


def test_disabled_config_is_inline_and_fault_site_still_fires():
    faults.arm("dispatch.sweep", "raise")
    with pytest.raises(InjectedFault):
        dispatch_with_retry(lambda: "x", None)
    with pytest.raises(InjectedFault):
        dispatch_with_retry(lambda: "x", DeadlineConfig(budget_s=0))


def test_hung_sweep_degrades_to_host_fallback_same_first_hit():
    """Acceptance: an injected hang in a device sweep dispatch raises
    DispatchTimeout within the configured budget, retries with backoff,
    then completes via the host-fallback path with the same first hit."""
    st, target, mask = build_planted_lut5_small()

    ref_ctx = SearchContext(Options(seed=1, lut_graph=True, randomize=False))
    ref = slut.lut5_search(ref_ctx, st, target, mask, [])
    assert ref is not None

    ctx = SearchContext(
        Options(seed=1, lut_graph=True, randomize=False,
                dispatch_timeout_s=0.3)
    )
    ctx.deadline_cfg.retries = 2
    ctx.deadline_cfg.backoff_s = 0.05
    faults.arm("dispatch.sweep", "hang")
    t0 = time.monotonic()
    try:
        res = slut.lut5_search(ctx, st, target, mask, [])
    finally:
        faults.disarm()
    # Bounded (vs the eternal hang without the guard): generous margin —
    # the window includes host-fallback jit compiles under CI load.
    assert time.monotonic() - t0 < 120.0
    assert ctx.stats["deadline_breaches"] == 3
    assert ctx.stats["dispatch_retries"] == 2
    assert res == ref  # same first hit as the unfaulted device stream
    assert verify_lut5_result(st, target, mask, res)
    # Circuit breaker: the exhausted retry schedule trips the context, so
    # the NEXT search routes straight to the host driver — no fresh
    # budget*(retries+1) stall per node against a known-dead device.
    assert ctx.device_degraded
    faults.arm("dispatch.sweep", "hang")  # device path would hang again
    try:
        t0 = time.monotonic()
        res2 = slut.lut5_search(ctx, st, target, mask, [])
    finally:
        faults.disarm()
    assert res2 == ref
    assert ctx.stats["deadline_breaches"] == 3  # no new breaches


def test_host_sync_deadline_fails_loudly_not_forever():
    """The host-fallback drivers' verdict syncs run under a deadline-only
    guard (no retry, no fault site): a dead device surfaces as a loud
    DispatchTimeout instead of an eternal hang — and the guard never
    re-enters the dispatch.sweep site it degrades away from."""
    ctx = SearchContext(Options(dispatch_timeout_s=0.1))
    ctx.deadline_cfg.retries = 1
    faults.arm("dispatch.sweep", "raise")  # must NOT fire on this path
    try:
        assert ctx.host_sync_deadline(lambda: 5, "host") == 5
        t0 = time.monotonic()
        with pytest.raises(DispatchTimeout):
            ctx.host_sync_deadline(lambda: time.sleep(30), "host")
        # One window of the whole retry schedule's budget: 0.1 * (1+1).
        assert time.monotonic() - t0 < 5.0
    finally:
        faults.disarm()
    # Disabled config: inline call, no threads.
    assert SearchContext(Options()).host_sync_deadline(lambda: 7, "h") == 7


def test_options_timeout_reaches_context_config():
    ctx = SearchContext(Options(dispatch_timeout_s=12.5))
    assert ctx.deadline_cfg.budget_s == 12.5
    assert ctx.deadline_cfg.enabled
    ctx2 = SearchContext(Options())
    assert not ctx2.deadline_cfg.enabled  # default: off


def test_guarded_dispatch_counts_into_ctx_stats():
    ctx = SearchContext(Options(dispatch_timeout_s=0.1))
    ctx.deadline_cfg.retries = 1
    ctx.deadline_cfg.backoff_s = 0.01
    with pytest.raises(DispatchTimeout):
        ctx.guarded_dispatch(lambda: time.sleep(10), "test")
    assert ctx.stats["deadline_breaches"] == 2
    assert ctx.stats["dispatch_retries"] == 1
    # The counters ride the normal stats channel (bench.py reports them
    # alongside the sync/compile guard tallies).
    assert "deadline_breaches" in SearchContext(Options()).stats


def test_lut7_device_timeout_degrades_to_host_chunks():
    """7-LUT stage A: a hung feasible-stream dispatch degrades to the
    host-chunked driver with an identical hit list."""
    rng = np.random.default_rng(3)
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import GATES, State

    st = State.init_inputs(8)
    while st.num_gates < 12:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    outer = tt.eval_lut(0x96, st.table(1), st.table(3), st.table(9))
    middle = tt.eval_lut(0xE8, st.table(2), st.table(5), st.table(10))
    target = tt.eval_lut(0xCA, outer, middle, st.table(7))
    mask = tt.mask_table(8)

    ref_ctx = SearchContext(Options(seed=2, lut_graph=True, randomize=False))
    ref = slut._lut7_collect_hits(ref_ctx, st, target, mask, [])

    ctx = SearchContext(
        Options(seed=2, lut_graph=True, randomize=False,
                dispatch_timeout_s=0.3)
    )
    ctx.deadline_cfg.retries = 1
    ctx.deadline_cfg.backoff_s = 0.01
    faults.arm("dispatch.sweep", "hang")
    try:
        got = slut._lut7_collect_hits(ctx, st, target, mask, [])
    finally:
        faults.disarm()
    assert ctx.stats["deadline_breaches"] >= 2
    for a, b in zip(ref, got):
        assert np.array_equal(a, b)
    # The abandoned device windows' candidate tally was backed out, so
    # the degraded run's accounting matches the reference sweep's.
    assert (
        ctx.stats["lut7_candidates"] == ref_ctx.stats["lut7_candidates"]
    )


# -- replicated degradation protocol (process-spanning meshes) -------------


CFG = dict(budget_s=0.3, retries=2, backoff_s=0.01)


def test_replicated_agreed_ok_returns_local_result():
    """Happy path: one verdict barrier per window, local result returned
    on an agreed OK."""
    verdicts = []

    def verdict(breached):
        verdicts.append(breached)
        return breached  # any(): nobody else breached

    stats = {}
    out = replicated_dispatch_with_retry(
        lambda: "ok", DeadlineConfig(**CFG), verdict, stats=stats
    )
    assert out == "ok"
    assert verdicts == [False]
    assert stats["breach_barriers"] == 1
    assert stats.get("replicated_aborts", 0) == 0
    assert stats.get("deadline_breaches", 0) == 0


def test_replicated_peer_breach_aborts_local_success():
    """A PEER's breach aborts this rank's locally-successful window: the
    result is discarded, the dispatch re-issued, and the retry's agreed
    OK returns the fresh result — the lockstep-abort half of the
    protocol."""
    script = iter([True, False])  # window 1: peer breached; window 2: ok
    reissues = []
    stats = {}
    out = replicated_dispatch_with_retry(
        lambda: "ok",
        DeadlineConfig(**CFG),
        lambda breached: next(script),
        stats=stats,
        on_retry=lambda: reissues.append(1),
    )
    assert out == "ok"
    assert reissues == [1]
    assert stats["breach_barriers"] == 2
    assert stats["replicated_aborts"] == 1
    assert stats["dispatch_retries"] == 1
    assert stats.get("deadline_breaches", 0) == 0  # local never breached


def test_replicated_exhaustion_raises_in_lockstep():
    """Agreed breaches through the whole schedule: every rank raises
    DispatchTimeout in the SAME window (the callers' degrade + circuit
    breaker then flip in lockstep), and degraded_ranks counts it."""
    stats = {}
    with pytest.raises(DispatchTimeout):
        replicated_dispatch_with_retry(
            lambda: "ok",
            DeadlineConfig(budget_s=0.2, retries=1, backoff_s=0.01),
            lambda breached: True,
            stats=stats,
        )
    assert stats["replicated_aborts"] == 2
    assert stats["dispatch_retries"] == 1
    assert stats["degraded_ranks"] == 1


def test_replicated_local_breach_and_hung_verdict_barrier():
    """A local breach is counted AND agreed; an unreachable verdict
    barrier (dist.verdict hang — a killed rank never answering) is
    itself treated as an agreed breach, so survivors abort together
    instead of waiting forever."""
    faults.arm("dispatch.sweep", "hang")
    stats = {}
    with pytest.raises(DispatchTimeout):
        replicated_dispatch_with_retry(
            lambda: "x",
            DeadlineConfig(budget_s=0.1, retries=0),
            lambda breached: breached,
            stats=stats,
        )
    assert stats["deadline_breaches"] == 1
    assert stats["replicated_aborts"] == 1
    faults.disarm()

    faults.arm("dist.verdict", "hang")
    stats = {}
    t0 = time.monotonic()
    with pytest.raises(DispatchTimeout):
        replicated_dispatch_with_retry(
            lambda: "x",
            DeadlineConfig(budget_s=0.2, retries=0),
            lambda breached: False,  # never reached: the watcher hangs
            stats=stats,
        )
    # Bounded by the watcher's abandon bound (transport timeout 2b+1
    # plus its fixed margin), not eternal.
    assert time.monotonic() - t0 < 12.0
    assert stats["replicated_aborts"] == 1
    assert stats.get("deadline_breaches", 0) == 0


def test_replicated_verdict_error_propagates():
    """Verdict-transport errors are loud bugs, not breach signals."""
    faults.arm("dist.verdict", "raise")
    with pytest.raises(InjectedFault):
        replicated_dispatch_with_retry(
            lambda: "x", DeadlineConfig(**CFG), lambda breached: False
        )


def test_replicated_disabled_config_is_inline():
    faults.arm("dispatch.sweep", "raise")
    with pytest.raises(InjectedFault):
        replicated_dispatch_with_retry(lambda: "x", None, lambda b: False)
    with pytest.raises(InjectedFault):
        replicated_dispatch_with_retry(
            lambda: "x", DeadlineConfig(budget_s=0), lambda b: False
        )


def test_rank_targeted_fault_sites():
    """SITE@rank:N fires only on the matching process rank — how the
    multi-process harness hangs/kills exactly one rank of a pod."""
    faults.arm("dispatch.sweep@rank:0", "raise")
    faults.set_rank(1)
    assert dispatch_with_retry(lambda: "x", None) == "x"  # wrong rank
    assert faults.hit_count("dispatch.sweep@rank:0") == 0
    faults.set_rank(0)
    with pytest.raises(InjectedFault):
        dispatch_with_retry(lambda: "x", None)
    assert faults.hit_count("dispatch.sweep@rank:0") == 1
    # Spec syntax: the site may carry the @rank:N suffix inside an
    # SBG_FAULTS value; malformed colons still fail loudly.
    spec = faults.parse_spec("dispatch.sweep@rank:1:hang@2")
    assert "dispatch.sweep@rank:1" in spec
    with pytest.raises(ValueError):
        faults.parse_spec("dispatch:sweep:hang")
    # Arming BOTH the plain site and a rank-qualified variant honors
    # both schedules, each on its own hit counter.
    faults.disarm()
    faults.set_rank(1)
    faults.arm("dispatch.sweep", "raise", "2")
    faults.arm("dispatch.sweep@rank:1", "raise", "1")
    with pytest.raises(InjectedFault):  # rank spec fires on its hit 1
        dispatch_with_retry(lambda: "x", None)
    with pytest.raises(InjectedFault):  # plain spec fires on its hit 2
        dispatch_with_retry(lambda: "x", None)
    assert faults.hit_count("dispatch.sweep") == 2
    assert faults.hit_count("dispatch.sweep@rank:1") == 2


def test_guarded_dispatch_routes_spanning_mesh_through_protocol(
    monkeypatch,
):
    """SearchContext.guarded_dispatch on a process-spanning mesh runs the
    replicated protocol (default ON now — SBG_DISPATCH_TIMEOUT_MULTIHOST
    is an opt-out), and exhaustion raises the lockstep DispatchTimeout
    the drivers degrade on."""
    from sboxgates_tpu.parallel import MeshPlan, make_mesh
    from sboxgates_tpu.parallel import distributed as dist

    ctx = SearchContext(
        Options(dispatch_timeout_s=0.2), mesh_plan=MeshPlan(make_mesh())
    )
    ctx.mesh_plan.spans_processes = True  # simulate a pod-wide mesh
    ctx.deadline_cfg.retries = 1
    ctx.deadline_cfg.backoff_s = 0.01
    seen = []

    def fake_verdict(breached, timeout_s=None):
        seen.append(breached)
        return bool(breached)

    monkeypatch.setattr(dist, "breach_verdict", fake_verdict)
    assert ctx.guarded_dispatch(lambda: 7, "t") == 7
    assert seen == [False]
    assert ctx.stats["breach_barriers"] == 1
    faults.arm("dispatch.sweep", "hang")
    with pytest.raises(DispatchTimeout):
        ctx.guarded_dispatch(lambda: 7, "t")
    faults.disarm()
    assert ctx.stats["degraded_ranks"] == 1
    assert ctx.stats["replicated_aborts"] == 2
    # Opt-out: SBG_DISPATCH_TIMEOUT_MULTIHOST=0 drops the guard entirely
    # on the spanning mesh (an unreplicated abort would deadlock peers).
    ctx.deadline_cfg.multihost = False
    seen.clear()
    assert ctx.guarded_dispatch(lambda: 9, "t") == 9
    assert seen == []


def test_single_host_guarded_dispatch_zero_barriers(monkeypatch):
    """Single-host behavior unchanged: guarded dispatch on a
    NON-spanning mesh (and with no mesh) takes ZERO verdict-barrier
    round trips, and first hits stay bit-identical with the protocol
    compiled in."""
    from sboxgates_tpu.parallel import MeshPlan, make_mesh
    from sboxgates_tpu.parallel import distributed as dist

    def boom(*a, **k):
        raise AssertionError("verdict barrier on a non-spanning mesh")

    monkeypatch.setattr(dist, "breach_verdict", boom)
    st, target, mask = build_planted_lut5_small()
    ref = slut.lut5_search(
        SearchContext(Options(seed=1, lut_graph=True, randomize=False)),
        st, target, mask, [],
    )
    assert ref is not None
    for mesh_plan in (None, MeshPlan(make_mesh())):
        ctx = SearchContext(
            Options(seed=1, lut_graph=True, randomize=False,
                    dispatch_timeout_s=30.0),
            mesh_plan=mesh_plan,
        )
        assert slut.lut5_search(ctx, st, target, mask, []) == ref
        assert ctx.stats["breach_barriers"] == 0
        assert ctx.stats["replicated_aborts"] == 0
