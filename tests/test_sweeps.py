"""Device sweep kernels vs. brute-force numpy oracles."""

import os
import numpy as np
import jax.numpy as jnp
import pytest

from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.ops import combinatorics as comb
from sboxgates_tpu.ops import sweeps


def random_tables(rng, n):
    return tt.from_bits(rng.integers(0, 2, size=(n, 256)).astype(bool))


# -- oracle implementations ----------------------------------------------


def oracle_feasible(tabs, target, mask, k):
    """check_n_lut_possible oracle: partition positions by input pattern."""
    bits = [tt.to_bits(tabs[i]) for i in range(k)]
    tbits = tt.to_bits(target)
    mbits = tt.to_bits(mask)
    idx = np.zeros(256, dtype=int)
    for i in range(k):
        idx = (idx << 1) | bits[i].astype(int)
    for cell in range(1 << k):
        sel = (idx == cell) & mbits
        if sel.any() and tbits[sel].any() and (~tbits[sel]).any():
            return False
    return True


def oracle_lut_function(tabs, target, mask):
    """get_lut_function oracle for 3 inputs: (func, setmask) or None."""
    bits = [tt.to_bits(t) for t in tabs]
    tbits, mbits = tt.to_bits(target), tt.to_bits(mask)
    func, setmask = 0, 0
    for pos in range(256):
        if not mbits[pos]:
            continue
        cell = (int(bits[0][pos]) << 2) | (int(bits[1][pos]) << 1) | int(bits[2][pos])
        want = int(tbits[pos])
        if setmask & (1 << cell):
            if ((func >> cell) & 1) != want:
                return None
        else:
            func |= want << cell
            setmask |= 1 << cell
    return func, setmask


# -- cell constraints ----------------------------------------------------


def test_cell_constraints_match_oracle(rng):
    tables = random_tables(rng, 8)
    target = tt.from_bits(rng.integers(0, 2, 256).astype(bool))
    mask = tt.mask_table(8)
    for k in (2, 3, 5):
        combos = np.asarray(
            list(__import__("itertools").combinations(range(8), k)), dtype=np.int32
        )
        tabs = jnp.asarray(tables)[jnp.asarray(combos)]
        req1, req0 = sweeps._cell_constraints(
            tabs, jnp.asarray(target), jnp.asarray(mask)
        )
        # transposed contract: [cells, N]
        req1, req0 = np.asarray(req1).T, np.asarray(req0).T
        for row, combo in enumerate(combos):
            feas_oracle = oracle_feasible(tables[combo], target, mask, k)
            feas_got = not (req1[row] & req0[row]).any()
            assert feas_got == feas_oracle, (k, combo)


def test_cell_constraints_lut_function(rng):
    """For feasible triples, req1/constrained must equal the oracle's
    derived LUT function and set-mask."""
    tables = random_tables(rng, 6)
    # use a target expressible from the tables so some triples are feasible
    target = tt.eval_lut(0xC5, tables[0], tables[1], tables[2])
    mask = tt.mask_table(8)
    combos = np.asarray(
        list(__import__("itertools").combinations(range(6), 3)), dtype=np.int32
    )
    tabs = jnp.asarray(tables)[jnp.asarray(combos)]
    req1, req0 = sweeps._cell_constraints(tabs, jnp.asarray(target), jnp.asarray(mask))
    req1, req0 = np.asarray(req1).T, np.asarray(req0).T
    feasible_rows = 0
    for row, combo in enumerate(combos):
        oracle = oracle_lut_function([tables[c] for c in combo], target, mask)
        if oracle is None:
            assert (req1[row] & req0[row]).any(), combo
            continue
        feasible_rows += 1
        func, setmask = oracle
        r = sum(int(req1[row][j]) << j for j in range(8))
        c = sum(int(req1[row][j] | req0[row][j]) << j for j in range(8))
        assert c == setmask
        assert r == func & setmask
    assert feasible_rows >= 1  # triple (0,1,2) at least


# -- match tables --------------------------------------------------------


def test_build_match_table_pairs():
    funs = [0b0001, 0b0110]  # AND, XOR in cell order? no — raw bytes
    table = sweeps.build_match_table(funs, num_cells=4)
    # R=0b0001 (cell0 ->1), C=0b1111: only fun 0 matches exactly
    assert table[0b0001 | (0b1111 << 4)] == 0
    # R=0b0110, C=0b1111: fun 1
    assert table[0b0110 | (0b1111 << 4)] == 1
    # R=0, C=0 (no constraints): first fun wins
    assert table[0] == 0
    # R=0b1111, C=0b1111: no match
    assert table[0b1111 | (0b1111 << 4)] == -1
    # partially constrained: C=0b0011, R=0b0010 matches XOR (0b0110)
    assert table[0b0010 | (0b0011 << 4)] == 1


def test_tuple_match_sweep_finds_pair(rng):
    """Plant a pair whose AND equals the target; the sweep must find it."""
    from sboxgates_tpu.search.context import _build_pair_table

    tables = random_tables(rng, 10)
    target = tables[2] & tables[7]
    mask = tt.mask_table(8)
    jtable, entries = _build_pair_table(
        bf.create_avail_gates(bf.DEFAULT_AVAILABLE)
    )
    i, j = np.triu_indices(10, k=1)
    combos = np.stack([i, j], axis=1).astype(np.int32)
    res = sweeps.tuple_match_sweep(
        jnp.asarray(tables),
        jnp.asarray(combos),
        jnp.ones(len(combos), dtype=bool),
        jnp.asarray(target),
        jnp.asarray(mask),
        jtable,
        0,
        num_cells=4,
    )
    res = np.asarray(res)  # packed [found, index, slot, num_feasible]
    assert bool(res[0])
    pair = combos[int(res[1])]
    entry = entries[int(res[2])]
    gids = [int(pair[p]) for p in entry.perm]
    got = tt.eval_gate2(entry.fun.fun, tables[gids[0]], tables[gids[1]])
    if entry.fun.not_out:
        got = ~got
    assert bool(tt.eq_mask(got, target, mask))


def test_tuple_match_sweep_noncommutative(rng):
    """A_AND_NOT_B requires operand-order handling."""
    from sboxgates_tpu.search.context import _build_pair_table

    tables = random_tables(rng, 6)
    # plant: tables[1] & ~tables[4] — only expressible with the right order
    target = ~tables[1] & tables[4]
    mask = tt.mask_table(8)
    funs = [bf.create_2_input_fun(bf.A_AND_NOT_B)]
    jtable, entries = _build_pair_table(funs)
    i, j = np.triu_indices(6, k=1)
    combos = np.stack([i, j], axis=1).astype(np.int32)
    res = sweeps.tuple_match_sweep(
        jnp.asarray(tables),
        jnp.asarray(combos),
        jnp.ones(len(combos), dtype=bool),
        jnp.asarray(target),
        jnp.asarray(mask),
        jtable,
        1,
        num_cells=4,
    )
    res = np.asarray(res)
    assert bool(res[0])
    pair = combos[int(res[1])]
    entry = entries[int(res[2])]
    gids = [int(pair[p]) for p in entry.perm]
    got = tt.eval_gate2(bf.A_AND_NOT_B, tables[gids[0]], tables[gids[1]])
    assert bool(tt.eq_mask(got, target, mask))


def test_match_scan(rng):
    tables = random_tables(rng, 12)
    mask = tt.mask_table(8)
    v = np.asarray(
        sweeps.match_scan(
            jnp.asarray(tables),
            jnp.ones(12, dtype=bool),
            jnp.asarray(tables[5]),
            jnp.asarray(mask),
            7,
        )
    )
    assert bool(v[0]) and not bool(v[2]) and int(v[1]) == 5
    v = np.asarray(
        sweeps.match_scan(
            jnp.asarray(tables),
            jnp.ones(12, dtype=bool),
            jnp.asarray(~tables[3]),
            jnp.asarray(mask),
            7,
        )
    )
    assert bool(v[0]) and bool(v[2]) and int(v[1]) == 3


# -- LUT kernels ---------------------------------------------------------


def test_lut3_stream_planted(rng):
    from sboxgates_tpu.ops import combinatorics as comb

    tables = random_tables(rng, 8)
    target = tt.eval_lut(0x3A, tables[1], tables[4], tables[6])
    mask = tt.mask_table(8)
    binom = jnp.asarray(sweeps.binom_table())
    excl = jnp.asarray(np.full(8, -1, np.int32))
    total = comb.n_choose_k(8, 3)
    v = np.asarray(
        sweeps.lut3_stream(
            jnp.asarray(tables), binom, 8, jnp.asarray(target),
            jnp.asarray(mask), excl, 0, total, 3, chunk=64,
        )
    )
    assert bool(v[0])
    row = comb.unrank_combination(int(v[1]), 8, 3)
    func = int(v[2]) & 0xFF  # don't-cares zero
    got = tt.eval_lut(
        func, tables[row[0]], tables[row[1]], tables[row[2]]
    )
    assert bool(tt.eq_mask(got, target, mask))


def test_lut5_pipeline_planted(rng):
    """Plant LUT(LUT(a,b,c),d,e); filter + solve must recover a valid
    decomposition."""
    tables = random_tables(rng, 9)
    a, b, c, d, e = 0, 2, 4, 6, 8
    outer = tt.eval_lut(0x5B, tables[a], tables[b], tables[c])
    target = tt.eval_lut(0xC9, outer, tables[d], tables[e])
    mask = tt.mask_table(8)
    combos = np.asarray(
        list(__import__("itertools").combinations(range(9), 5)), dtype=np.int32
    )
    feas, req1p, req0p = sweeps.lut_filter(
        jnp.asarray(tables),
        jnp.asarray(combos),
        jnp.ones(len(combos), dtype=bool),
        jnp.asarray(target),
        jnp.asarray(mask),
    )
    feas = np.asarray(feas)
    assert feas.any()
    # the planted tuple must be feasible
    planted = [a, b, c, d, e]
    planted_row = next(
        i for i, row in enumerate(combos) if list(row) == planted
    )
    assert feas[planted_row]

    splits, w_tab, m_tab = sweeps.lut5_split_tables()
    fidx = np.nonzero(feas)[0]
    v = np.asarray(
        sweeps.lut5_solve(
            jnp.asarray(np.asarray(req1p)[fidx]),
            jnp.asarray(np.asarray(req0p)[fidx]),
            jnp.asarray(w_tab),
            jnp.asarray(m_tab),
            5,
        )
    )
    assert bool(v[0])
    t = int(v[1])
    sigma, func_outer = divmod(int(v[2]), 256)
    combo = combos[fidx[t]]
    ga, gb, gc, gd, ge = (int(combo[p]) for p in splits[sigma])
    req1_cells = ((int(np.asarray(req1p)[fidx][t]) >> np.arange(32)) & 1).astype(bool)
    req0_cells = ((int(np.asarray(req0p)[fidx][t]) >> np.arange(32)) & 1).astype(bool)
    wbits = ((int(w_tab[sigma, func_outer]) >> np.arange(32)) & 1).astype(bool)
    groups = np.zeros(32, dtype=np.int64)
    for m in range(4):
        mm = ((int(m_tab[sigma, m]) >> np.arange(32)) & 1).astype(bool)
        groups[mm & wbits] = 4 + m
        groups[mm & ~wbits] = m
    func_inner = sweeps.solve_inner_function(req1_cells, req0_cells, groups, None)
    assert func_inner is not None
    outer_t = tt.eval_lut(func_outer, tables[ga], tables[gb], tables[gc])
    inner_t = tt.eval_lut(func_inner, outer_t, tables[gd], tables[ge])
    assert bool(tt.eq_mask(inner_t, target, mask))


def test_lut7_pair_formulation_matches_group_oracle(rng):
    """The pair-agreement bilinear form used by lut7_solve must agree with
    the direct 'no inner-LUT group mixes required-1 and required-0 cells'
    test for random constraints and decompositions."""
    orders, wo_tab, wm_tab, g_tab = sweeps.lut7_split_tables()
    idx_tab, pp_tab = sweeps.lut7_pair_tables()

    def unpack(words):
        return np.concatenate(
            [((int(w) >> np.arange(32)) & 1) for w in words]
        ).astype(bool)

    for _ in range(100):
        sigma = int(rng.integers(0, len(orders)))
        fo = int(rng.integers(0, 256))
        fm = int(rng.integers(0, 256))
        cells = rng.integers(0, 3, size=128)  # 0: free, 1: req1, 2: req0
        r1 = cells == 1
        r0 = cells == 2

        # Direct oracle: group cells by (fo output, fm output, free bit).
        wob = unpack(wo_tab[sigma, fo])
        wmb = unpack(wm_tab[sigma, fm])
        gb = unpack(g_tab[sigma])
        groups = wob * 4 + wmb * 2 + gb
        conflict = any(
            (r1 & (groups == g)).any() and (r0 & (groups == g)).any()
            for g in range(8)
        )

        # Pair formulation: PP[fo] . B . PP[fm]^T > 0.
        a1 = r1[idx_tab[sigma]].reshape(2, 8, 8).astype(np.float64)
        a0 = r0[idx_tab[sigma]].reshape(2, 8, 8).astype(np.float64)
        b = np.einsum("xpq,xrs->prqs", a1, a0).reshape(64, 64)
        c = pp_tab[fo] @ b @ pp_tab[fm]
        assert (c > 0) == conflict, (sigma, fo, fm)


def test_lut7_pipeline_planted(rng):
    """Plant LUT(LUT(a,b,c),LUT(d,e,f),g); the 7-LUT solver must recover a
    valid decomposition."""
    tables = random_tables(rng, 7)
    outer = tt.eval_lut(0x1D, tables[0], tables[1], tables[2])
    middle = tt.eval_lut(0xB2, tables[3], tables[4], tables[5])
    target = tt.eval_lut(0x6A, outer, middle, tables[6])
    mask = tt.mask_table(8)
    combos = np.asarray([[0, 1, 2, 3, 4, 5, 6]], dtype=np.int32)
    feas, req1p, req0p = sweeps.lut_filter(
        jnp.asarray(tables),
        jnp.asarray(combos),
        jnp.ones(1, dtype=bool),
        jnp.asarray(target),
        jnp.asarray(mask),
    )
    assert bool(np.asarray(feas)[0])
    orders, wo_tab, wm_tab, g_tab = sweeps.lut7_split_tables()
    idx_tab, pp_tab = sweeps.lut7_pair_tables()
    v = np.asarray(
        sweeps.lut7_solve(
            jnp.asarray(req1p),
            jnp.asarray(req0p),
            jnp.asarray(idx_tab),
            jnp.asarray(pp_tab),
            11,
        )
    )
    assert bool(v[0])
    sigma = int(v[2])
    func_outer, func_middle = divmod(int(v[3]), 256)
    order = orders[sigma]
    req1_cells = np.concatenate(
        [((int(w) >> np.arange(32)) & 1) for w in np.asarray(req1p)[0]]
    ).astype(bool)
    req0_cells = np.concatenate(
        [((int(w) >> np.arange(32)) & 1) for w in np.asarray(req0p)[0]]
    ).astype(bool)
    wobits = np.concatenate(
        [((int(w) >> np.arange(32)) & 1) for w in wo_tab[sigma, func_outer]]
    ).astype(bool)
    wmbits = np.concatenate(
        [((int(w) >> np.arange(32)) & 1) for w in wm_tab[sigma, func_middle]]
    ).astype(bool)
    gbits = np.concatenate(
        [((int(w) >> np.arange(32)) & 1) for w in g_tab[sigma]]
    ).astype(bool)
    groups = wobits * 4 + wmbits * 2 + gbits * 1
    func_inner = sweeps.solve_inner_function(
        req1_cells, req0_cells, groups.astype(np.int64), None
    )
    assert func_inner is not None
    a, b, c, d, e, f = (int(combos[0][p]) for p in order[:6])
    gg = int(combos[0][order[6]])
    t_outer = tt.eval_lut(func_outer, tables[a], tables[b], tables[c])
    t_middle = tt.eval_lut(func_middle, tables[d], tables[e], tables[f])
    t_inner = tt.eval_lut(func_inner, t_outer, t_middle, tables[gg])
    assert bool(tt.eq_mask(t_inner, target, mask))


# -- combinatorics -------------------------------------------------------


def test_unrank_and_stream():
    import itertools

    all_combos = list(itertools.combinations(range(9), 4))
    for r in (0, 1, 17, 125):
        assert tuple(comb.unrank_combination(r, 9, 4)) == all_combos[r]
        assert comb.combination_rank(all_combos[r], 9) == r
    # stream from an offset
    s = comb.CombinationStream(9, 4, start=100)
    chunk = s.next_chunk(1000)
    assert [tuple(row) for row in chunk] == all_combos[100:]
    assert s.next_chunk(10) is None


def test_stream_chunking():
    s = comb.CombinationStream(10, 3)
    seen = []
    while True:
        c = s.next_chunk(17)
        if c is None:
            break
        seen.extend(tuple(r) for r in c)
    import itertools

    assert seen == list(itertools.combinations(range(10), 3))


def test_filter_exclude():
    combos = np.asarray([[0, 1, 2], [1, 2, 3], [2, 3, 4]], dtype=np.int32)
    out = comb.filter_exclude(combos, [0, 4])
    assert [tuple(r) for r in out] == [(1, 2, 3)]


def test_host_cell_constraints_mirrors_device(rng):
    """The numpy mirror used for host-side decode must agree with the
    (transposed) device kernel."""
    tables = random_tables(rng, 9)
    target = tt.from_bits(rng.integers(0, 2, 256).astype(bool))
    mask = tt.mask_table(8)
    combos = np.asarray(
        list(__import__("itertools").combinations(range(9), 5)), dtype=np.int32
    )
    tabs = jnp.asarray(tables)[jnp.asarray(combos)]
    req1, req0 = sweeps._cell_constraints(tabs, jnp.asarray(target), jnp.asarray(mask))
    req1, req0 = np.asarray(req1).T, np.asarray(req0).T
    for row in (0, 17, len(combos) - 1):
        h1, h0 = sweeps.host_cell_constraints(tables, combos[row], target, mask)
        assert (h1 == req1[row]).all() and (h0 == req0[row]).all(), row


# -- pivot-structured 5-LUT sweep ----------------------------------------


def test_pivot_tiles_cover_space_exactly():
    from sboxgates_tpu.ops import combinatorics as comb

    for g in (6, 9, 22, 40):
        descs = sweeps.pivot_tile_descs(g, 16, 32)
        sizes = (descs[:, 2] - descs[:, 1]) * (descs[:, 4] - descs[:, 3])
        assert sizes.sum() == comb.n_choose_k(g, 5), g
        # every tile's rows land inside its pivot's valid ranges
        lows, highs, offs = sweeps.pivot_pair_grids(g)
        for m, lo0, lo_end, hi0, hi_end in descs:
            assert lo_end <= m * (m - 1) // 2
            assert (lows[lo0:lo_end] < m).all()
            assert (highs[hi0:hi_end] > m).all()


def test_pivot_search_finds_planted_decomposition(rng):
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.lut import _lut5_search_pivot

    st = State.init_inputs(8)
    nprng = np.random.default_rng(11)
    while st.num_gates < 22:
        a, b = nprng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    outer = tt.eval_lut(0x2D, st.table(3), st.table(8), st.table(14))
    target = tt.eval_lut(0xB4, outer, st.table(5), st.table(19))
    mask = tt.mask_table(8)
    ctx = SearchContext(Options(seed=2, lut_graph=True))
    res = _lut5_search_pivot(ctx, st, target, mask, [])
    assert res is not None
    a, b, c, d, e = res["gates"]
    got = tt.eval_lut(
        res["func_inner"],
        tt.eval_lut(res["func_outer"], st.table(a), st.table(b), st.table(c)),
        st.table(d),
        st.table(e),
    )
    assert bool(tt.eq_mask(got, target, mask))
    assert ctx.stats["lut5_candidates"] > 0


def test_pivot_pallas_backend_bit_identical():
    """The fused Pallas pivot kernel (ops/pallas_pivot.py, interpreter
    mode here) must produce the byte-identical stream verdict as the XLA
    backend — hits, constraint words, and resume tile — alone and
    composed with the pipeline lever, at BOTH production tile shapes
    ((256, 512) for small G — what pivot_tile_shape(50) selects — and
    (512, 512) for G > 128, the shape every large search uses)."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    import jax.numpy as jnp
    from planted import build_planted_lut5

    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.lut import PivotOperands, pivot_tile_shape

    st, target, mask = build_planted_lut5()
    g = st.num_gates
    assert pivot_tile_shape(g) == (256, 512)
    for tl, th in ((256, 512), (512, 512)):
        ctx = SearchContext(Options(seed=1, lut_graph=True, randomize=False))
        dev_tables = ctx.device_tables(st)
        ops = PivotOperands(
            g, tl, th, [], dev_tables, target, mask, ctx.place_replicated
        )
        _, w_tab, m_tab = sweeps.lut5_split_tables()
        jw, jm = jnp.asarray(w_tab), jnp.asarray(m_tab)
        args = ops.stream_args()
        base = np.asarray(
            sweeps.lut5_pivot_stream(
                *args, 0, ops.t_real, jw, jm, -1, tl=tl, th=th
            )
        )
        for backend in ("pallas", "pallas_pre", "xla_bf16", "xla_f8"):
            for pipeline in (False, True):
                got = np.asarray(
                    sweeps.lut5_pivot_stream(
                        *args, 0, ops.t_real, jw, jm, -1, tl=tl, th=th,
                        backend=backend, pipeline=pipeline,
                    )
                )
                assert (base == got).all(), (tl, th, backend, pipeline)
        # The "pallas[_pre]:BLxBH" static block variants (the bench's
        # on-chip block-shape ladder) must hit the same bits as the
        # default block — one non-default shape at the small tile
        # suffices to cover the parse + partial plumbing.
        if (tl, th) == (256, 512):
            for backend in ("pallas:128x128", "pallas_pre:128x128"):
                got = np.asarray(
                    sweeps.lut5_pivot_stream(
                        *args, 0, ops.t_real, jw, jm, -1, tl=tl, th=th,
                        backend=backend,
                    )
                )
                assert (base == got).all(), (tl, th, backend)
        assert int(base[0]) == 1  # the planted decomposition was found


def test_pivot_search_respects_exclusions(rng):
    """With every planted gate excluded, the sweep must find nothing (the
    target is otherwise unrealizable from XOR combinations)."""
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.lut import _lut5_search_pivot
    from sboxgates_tpu.utils.sbox import load_sbox
    import os

    sbox, n = load_sbox(
        os.path.join(os.path.dirname(__file__), "data", "rijndael.txt")
    )
    st = State.init_inputs(8)
    nprng = np.random.default_rng(3)
    while st.num_gates < 20:
        a, b = nprng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    target = tt.target_table(sbox, 0)  # not 5-LUT realizable from XOR layers
    mask = tt.mask_table(8)
    ctx = SearchContext(Options(seed=2, lut_graph=True))
    assert _lut5_search_pivot(ctx, st, target, mask, [1, 4]) is None


# -- wide (64-bit) rank streaming ------------------------------------------


def test_wide_unrank_matches_host_at_big_ranks():
    """Pair-arithmetic unranking parity with the host reference at ranks
    past int32 (C(200, 5) ~ 2.5e9)."""
    import jax
    import jax.numpy as jnp

    blo, bhi = sweeps.binom_table_wide()
    g, k = 200, 5
    total = comb.n_choose_k(g, k)
    assert total > 2**31
    ranks = [0, 1, 123456, 2**31 - 1, 2**31, 2**31 + 12345, total - 1]
    rlo = np.array([r & 0xFFFFFFFF for r in ranks], np.uint32)
    rhi = np.array([r >> 32 for r in ranks], np.uint32)
    out = np.asarray(jax.jit(
        lambda a, b: sweeps._unrank_combos_wide(
            jnp.asarray(blo), jnp.asarray(bhi), g, k, a, b
        )
    )(rlo, rhi))
    for i, r in enumerate(ranks):
        np.testing.assert_array_equal(
            out[:, i], comb.unrank_combination(r, g, k)
        )


def _wide_stream_case(rng, g=40, k=5, planted=True):
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.graph.state import GATES, State

    st = State.init_inputs(8)
    while st.num_gates < g:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    mask = tt.mask_table(8)
    if planted:
        target = tt.eval_lut(
            0x96, st.table(g - 10), st.table(g - 7), st.table(g - 3)
        )
    else:
        target = rng.integers(0, 2**32, size=8, dtype=np.uint32)
    tables = np.zeros((64, 8), np.uint32)
    tables[:g] = st.live_tables()
    return tables, target, mask


@pytest.mark.parametrize("excl", [(), (3, 17)])
def test_feasible_stream_wide_matches_int32_stream(rng, excl):
    """The 64-bit pair-arithmetic stream must return the identical
    verdict, chunk start, and constraint arrays as feasible_stream on a
    space both can express — including exclusion masking."""
    import jax.numpy as jnp

    g, k, chunk = 40, 5, 1024
    tables, target, mask = _wide_stream_case(rng, g, k)
    total = comb.n_choose_k(g, k)
    ex = np.full(8, -1, np.int32)
    for i, b in enumerate(excl):
        ex[i] = b
    blo, bhi = sweeps.binom_table_wide()
    for start in (0, total - 4 * chunk):
        vw, fw, r1w, r0w = sweeps.feasible_stream_wide(
            jnp.asarray(tables), jnp.asarray(blo), jnp.asarray(bhi), g,
            jnp.asarray(target), jnp.asarray(mask), jnp.asarray(ex),
            np.uint32(start & 0xFFFFFFFF), np.uint32(start >> 32),
            np.uint32(total & 0xFFFFFFFF), np.uint32(total >> 32),
            k=k, chunk=chunk,
        )
        vi, fi, r1i, r0i = sweeps.feasible_stream(
            jnp.asarray(tables), jnp.asarray(sweeps.binom_table()), g,
            jnp.asarray(target), jnp.asarray(mask), jnp.asarray(ex),
            start, total, k=k, chunk=chunk,
        )
        vw, vi = np.asarray(vw), np.asarray(vi)
        assert vw[0] == vi[0]
        cstart = int(np.uint32(vw[1])) | (int(np.uint32(vw[2])) << 32)
        assert cstart == int(vi[1])
        np.testing.assert_array_equal(np.asarray(fw), np.asarray(fi))
        np.testing.assert_array_equal(np.asarray(r1w), np.asarray(r1i))
        np.testing.assert_array_equal(np.asarray(r0w), np.asarray(r0i))


def test_device_feasible_chunks_matches_host_chunks(rng, monkeypatch):
    """The device-resident 64-bit enumeration and the ChunkPrefetcher
    host stream must surface the identical feasible rows (combos and
    packed constraint words) for the same space."""
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search import lut as slut
    from contextlib import closing

    from planted import build_planted_lut7

    st, target, mask = build_planted_lut7()

    def collect(route_env):
        monkeypatch.setenv("SBG_DEVICE_ENUM", route_env)
        ctx = SearchContext(Options(seed=7, warmup=False))
        hits = []
        chunks = slut._feasible_chunks(
            ctx, st, target, mask, [1], k=7, chunk_cap=8192,
            stat_key="lut7_candidates", phase="lut7.stageA",
        )
        with closing(chunks):
            for combos_fn, feas, r1, r0 in chunks:
                fidx = np.nonzero(feas)[0]
                hits.append((
                    combos_fn(fidx), np.asarray(r1)[fidx],
                    np.asarray(r0)[fidx],
                ))
        assert hits
        return (
            np.concatenate([h[0] for h in hits]),
            np.concatenate([h[1] for h in hits]),
            np.concatenate([h[2] for h in hits]),
        )

    dev = collect("1")
    host = collect("0")
    np.testing.assert_array_equal(dev[0], host[0])
    np.testing.assert_array_equal(dev[1], host[1])
    np.testing.assert_array_equal(dev[2], host[2])


# -- 5-LUT feasibility filter head (pallas backend) ------------------------


def test_lut5_filter_pallas_bit_identical(rng):
    """The fused VMEM filter kernel must produce the identical packed
    constraint words and feasibility verdicts as the XLA epilogue
    (interpreter mode on CPU)."""
    import jax.numpy as jnp

    tables, target, mask = _wide_stream_case(rng, g=40, k=5)
    combos = np.stack(
        [comb.unrank_combination(r, 40, 5) for r in range(1024)]
    ).astype(np.int32)
    valid = rng.integers(0, 2, size=1024).astype(bool)
    args = (
        jnp.asarray(tables), jnp.asarray(combos), jnp.asarray(valid),
        jnp.asarray(target), jnp.asarray(mask),
    )
    fx, r1x, r0x = sweeps.lut5_filter(*args, backend="xla")
    fp, r1p, r0p = sweeps.lut5_filter(*args, backend="pallas")
    np.testing.assert_array_equal(np.asarray(fx), np.asarray(fp))
    np.testing.assert_array_equal(np.asarray(r1x), np.asarray(r1p))
    np.testing.assert_array_equal(np.asarray(r0x), np.asarray(r0p))
    assert np.asarray(fx).any()


def test_feasible_stream_wide_pallas_backend_bit_identical(rng):
    """backend="pallas" inside the wide stream's while_loop must match
    the XLA epilogue bit for bit."""
    import jax.numpy as jnp

    g, k, chunk = 40, 5, 1024
    tables, target, mask = _wide_stream_case(rng, g, k)
    total = comb.n_choose_k(g, k)
    ex = np.full(8, -1, np.int32)
    blo, bhi = sweeps.binom_table_wide()
    args = (
        jnp.asarray(tables), jnp.asarray(blo), jnp.asarray(bhi), g,
        jnp.asarray(target), jnp.asarray(mask), jnp.asarray(ex),
        np.uint32(0), np.uint32(0),
        np.uint32(total & 0xFFFFFFFF), np.uint32(total >> 32),
    )
    outs = {}
    for backend in ("xla", "pallas"):
        v, f, r1, r0 = sweeps.feasible_stream_wide(
            *args, k=k, chunk=chunk, backend=backend
        )
        outs[backend] = (
            np.asarray(v), np.asarray(f), np.asarray(r1), np.asarray(r0)
        )
    for a, b in zip(outs["xla"], outs["pallas"]):
        np.testing.assert_array_equal(a, b)


# -- fused multi-round driver ----------------------------------------------


def _round_chain_case(n_rounds=10, seed=7, gates=12, deep_last=False):
    """Shared planted-chain fixture (tests/planted.py holds the one
    construction the driver tests and the resume tests both use)."""
    from planted import build_round_chain

    return build_round_chain(
        n_rounds=n_rounds, gates0=gates, seed=seed, deep_last=deep_last
    )


@pytest.mark.parametrize("seed", [None, 123, 999])
def test_round_chain_bit_identity_across_n(seed):
    """Fused N-round chains must produce byte-identical circuits to the
    per-round (N=1) loop for every rounds-per-dispatch and seed."""
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.rounds import run_round_chain

    sigs = []
    for n in (1, 2, 8):
        st, rounds = _round_chain_case()
        ctx = SearchContext(Options(
            lut_graph=True, randomize=seed is not None, seed=seed,
            warmup=False, parallel_mux=False,
        ))
        outs = run_round_chain(ctx, st, rounds, rounds_per_dispatch=n)
        for (tgt, msk), out in zip(rounds, outs):
            st.verify_gate(out, tgt, msk)
        sigs.append((
            tuple(outs), st.tables.tobytes(),
            tuple(
                (g.type, g.in1, g.in2, g.in3, g.function) for g in st.gates
            ),
        ))
        # every round completed on device (no fallback in this chain)
        assert ctx.stats["round_driver_fallbacks"] == 0
        assert ctx.stats["round_driver_rounds"] == len(rounds)
    assert sigs[0] == sigs[1] == sigs[2]


def test_round_chain_scan_kinds_and_fallback():
    """Existing-gate and complement rounds must not append LUTs, and a
    round the kernel cannot finish must run the host recursion — with
    the chain bit-identical across rounds-per-dispatch either way."""
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.rounds import run_round_chain

    st0, rounds = _round_chain_case(n_rounds=4, deep_last=True)
    mask = tt.mask_table(8)
    # Prepend a direct-match round (an input's own table) and a
    # complement round.
    rounds = [
        (st0.table(3).copy(), mask),
        ((~st0.table(5)).copy(), mask),
    ] + rounds
    sigs = []
    for n in (1, 8):
        st = st0.copy()
        ctx = SearchContext(Options(
            lut_graph=True, randomize=False, warmup=False,
            parallel_mux=False, native_engine=False,
        ))
        outs = run_round_chain(ctx, st, rounds, rounds_per_dispatch=n)
        assert outs[0] == 3  # direct match: no new gate
        for (tgt, msk), out in zip(rounds, outs):
            st.verify_gate(out, tgt, msk)
        assert ctx.stats["round_driver_fallbacks"] == 1
        sigs.append((tuple(outs), st.tables.tobytes()))
    assert sigs[0] == sigs[1]
