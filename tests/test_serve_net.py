"""Network admission service tests: the authenticated, quota-enforced,
drain-safe HTTP front door (sboxgates_tpu/serve_net/) exercised through
the REAL socket surface on ephemeral loopback ports.

The acceptance gates ride here end-to-end: a repeat POST of a stored
query answers 200 with the circuit and ZERO device dispatches;
concurrent duplicate POSTs yield ONE search and N joined clients with
bit-identical results; an ``os._exit`` kill between the admission-
journal append and the orchestrator enqueue loses nothing (restart
replays the journal and the job completes); a drain mid-load preserves
every admitted job for the next boot; and unauthorized / over-quota /
oversize / slow requests get 401/403/429/413/408 without touching the
orchestrator or the shared breaker.  The four ``net.*`` chaos sites are
armed here (kill-matrix coverage), plus the ``@tenant:`` targeting
form.  All tests except the crash-replay subprocess pair run
in-process on toy 3-input searches.
"""

import http.client
import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

from sboxgates_tpu.resilience import faults
from sboxgates_tpu.resilience.deadline import DeadlineConfig
from sboxgates_tpu.search import Options, SearchContext
from sboxgates_tpu.search.fleet import toy_fleet_boxes
from sboxgates_tpu.search.serve import ServeOrchestrator
from sboxgates_tpu.serve_net import (
    TokenFileError,
    TokenStore,
    check_file,
    write_token_file,
)
from sboxgates_tpu.serve_net.admission import AdmissionJournal, pending_jobs
from sboxgates_tpu.serve_net.server import AdmissionServer
from sboxgates_tpu.telemetry import metrics as tmetrics
from sboxgates_tpu.telemetry import status as tstatus

#: Device-dispatch options (mirrors tests/test_store.py DEVOPTS).
DEVOPTS = dict(
    seed=11, lut_graph=True, randomize=False, host_small_steps=False,
    native_engine=False, warmup=False,
)

@pytest.fixture(autouse=True)
def _clean_faults():
    faults.disarm()
    faults.set_tenant(None)
    yield
    faults.disarm()
    faults.set_tenant(None)


def toy_sbox_text(j=0):
    """One toy 3-input table in the POST wire format (hex text)."""
    box = toy_fleet_boxes(max(1, j + 1))[j].sbox
    return " ".join("%02x" % v for v in box[:8])


TENANTS = {
    "acme": {"token": "tok-acme", "max_jobs": 8,
             "rate_per_s": 500.0, "burst": 200},
    "bob": {"token": "tok-bob", "max_jobs": 1,
            "rate_per_s": 500.0, "burst": 200},
    "slow": {"token": "tok-slow", "max_jobs": 8,
             "rate_per_s": 0.001, "burst": 1},
    "off": {"token": "tok-off", "disabled": True},
}


def make_stack(tmp_path, sub="serve", store=None, read_timeout_s=10.0,
               tenants=TENANTS, retries=2):
    """Context + orchestrator + admission server on an ephemeral port
    (neither started — each test picks what runs)."""
    opts = dict(DEVOPTS)
    if store is not None:
        opts["result_store"] = store
    ctx = SearchContext(Options(**opts))
    root = str(tmp_path / sub)
    orch = ServeOrchestrator(
        ctx, root, lanes=2,
        deadline=DeadlineConfig(retries=retries, backoff_s=0.01),
        log=lambda s: None,
    )
    tok_path = str(tmp_path / f"{sub}-tokens.json")
    if not os.path.exists(tok_path):
        write_token_file(tok_path, tenants)
    srv = AdmissionServer(
        orch, TokenStore.load(tok_path), ctx.stats, root,
        read_timeout_s=read_timeout_s, log=lambda s: None,
    )
    return ctx, orch, srv


def req(port, method, path, body=None, token="tok-acme", idem=None,
        timeout=60):
    """One HTTP round trip; returns (status, parsed JSON body)."""
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    headers = {}
    if token is not None:
        headers["Authorization"] = f"Bearer {token}"
    if idem is not None:
        headers["Idempotency-Key"] = idem
    data = json.dumps(body) if isinstance(body, dict) else body
    try:
        c.request(method, path, body=data, headers=headers)
        r = c.getresponse()
        return r.status, json.loads(r.read().decode("utf-8"))
    finally:
        c.close()


def wait_no_pending(root, timeout_s=10.0):
    """The done marker lands just AFTER the terminal-state broadcast
    a long-poll GET rides, so give the journal a beat to settle."""
    deadline = time.monotonic() + timeout_s
    while pending_jobs(root) and time.monotonic() < deadline:
        time.sleep(0.05)
    return pending_jobs(root)


def post_job(port, sbox_text, output=0, token="tok-acme", idem=None,
             **extra):
    body = {"sbox": sbox_text, "output": output, **extra}
    return req(port, "POST", "/v1/jobs", body, token=token, idem=idem)


# -------------------------------------------------------------------------
# the admission surface end-to-end
# -------------------------------------------------------------------------


def test_post_longpoll_and_idempotent_repeat(tmp_path):
    """Happy path through the real socket: POST admits (202), the
    long-poll GET rides the job to DONE, a repeat POST answers 200
    with the circuit and ZERO new device dispatches, and a different
    Idempotency-Key is a different job."""
    ctx, orch, srv = make_stack(tmp_path)
    srv.start()
    orch.start()
    try:
        port = srv.port
        s, d = post_job(port, toy_sbox_text(0))
        assert s == 202 and d["state"] in ("queued", "running")
        jid = d["job_id"]
        assert jid.startswith("net-")

        # Long-poll to terminal: one bounded request, no client loop.
        s, d = req(port, "GET", f"/v1/jobs/{jid}?wait=60")
        assert s == 200 and d["state"] == "done", d
        assert d["circuits"] and d["circuits"][0]["xml"].strip()
        xml = d["circuits"][0]["xml"]

        # Idempotent repeat: 200 + the SAME circuit bytes, and the
        # search does not run again (no new device dispatches).
        before = int(ctx.stats.get("device_dispatches", 0))
        s, d = post_job(port, toy_sbox_text(0))
        assert s == 200 and d["state"] == "done"
        assert d["job_id"] == jid
        assert d["circuits"][0]["xml"] == xml
        assert int(ctx.stats.get("device_dispatches", 0)) == before
        assert int(ctx.stats.get("net_repeat_hits", 0)) >= 1

        # A different Idempotency-Key is a different admission.
        s, d = post_job(port, toy_sbox_text(0), idem="variant-1")
        assert s in (200, 202)
        assert d["job_id"] != jid

        # Unknown job and bad route are structured 404s.
        s, d = req(port, "GET", "/v1/jobs/net-ffffffffffffffff")
        assert s == 404 and d["error"]["code"] == "not_found"
        s, d = req(port, "GET", "/v1/nope")
        assert s == 404
        assert ctx.stats.undeclared() == set()
    finally:
        srv.close()
        orch.run_until_idle(timeout_s=60)
        orch.stop()


def test_stored_query_repeat_zero_dispatch_through_http(tmp_path):
    """Acceptance (a): a repeat POST of a STORED query — fresh process
    (new context/orchestrator), same result store — answers 200 with
    the circuit, `store: hit`, and zero device dispatches end to end."""
    store_dir = str(tmp_path / "store")
    ctx1, orch1, srv1 = make_stack(tmp_path, "a", store=store_dir)
    srv1.start()
    orch1.start()
    try:
        s, d = post_job(srv1.port, toy_sbox_text(1))
        assert s == 202
        s, d = req(srv1.port, "GET",
                   f"/v1/jobs/{d['job_id']}?wait=60")
        assert s == 200 and d["state"] == "done"
        xml1 = d["circuits"][0]["xml"]
    finally:
        srv1.close()
        orch1.run_until_idle(timeout_s=60)
        orch1.stop()
        ctx1.result_store.flush()
        ctx1.result_store.close()

    ctx2, orch2, srv2 = make_stack(tmp_path, "b", store=store_dir)
    srv2.start()
    orch2.start()
    try:
        s, d = post_job(srv2.port, toy_sbox_text(1))
        assert s == 200, d
        assert d["state"] == "done" and d["store"] == "hit"
        # Bit-identical to the fresh search's circuit, and the second
        # process made NO device dispatches at all.
        assert d["circuits"][0]["xml"] == xml1
        assert int(ctx2.stats.get("device_dispatches", 0)) == 0
        assert int(ctx2.stats.get("net_repeat_hits", 0)) == 1
    finally:
        srv2.close()
        orch2.stop()
        ctx2.result_store.close()


def test_concurrent_duplicate_posts_one_search_n_joined(tmp_path):
    """Acceptance (b): N concurrent identical POSTs admit exactly ONE
    search; the rest join in flight, and every client reads the same
    bit-identical circuit."""
    ctx, orch, srv = make_stack(tmp_path)
    srv.start()
    orch.start()
    n = 6
    barrier = threading.Barrier(n)
    results = [None] * n

    def client(i):
        barrier.wait()
        results[i] = post_job(srv.port, toy_sbox_text(2), idem="dup")

    try:
        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert all(r is not None for r in results)
        ids = {d["job_id"] for _, d in results}
        assert len(ids) == 1, ids
        jid = ids.pop()
        assert int(ctx.stats.get("net_jobs_admitted", 0)) == 1
        joined = int(ctx.stats.get("net_joined", 0))
        hits = int(ctx.stats.get("net_repeat_hits", 0))
        assert joined + hits == n - 1
        # One search: one job, one job directory, one journal.
        assert orch.active_jobs("acme") <= 1
        job_dirs = [
            f for f in os.listdir(orch.root) if f.startswith("net-")
        ]
        assert job_dirs == [jid]
        # Every client reads the same final bytes.
        xmls = set()
        for _ in range(2):  # cheap retry for scheduler timing
            s, d = req(srv.port, "GET", f"/v1/jobs/{jid}?wait=60")
            assert s == 200
            if d["state"] == "done":
                break
        assert d["state"] == "done"
        xmls.add(d["circuits"][0]["xml"])
        s2, d2 = post_job(srv.port, toy_sbox_text(2), idem="dup")
        assert s2 == 200
        xmls.add(d2["circuits"][0]["xml"])
        assert len(xmls) == 1
        assert orch.job(jid).joined == joined
    finally:
        srv.close()
        orch.run_until_idle(timeout_s=60)
        orch.stop()


# -------------------------------------------------------------------------
# rejections: 401/403/429/413/408 never touch the orchestrator
# -------------------------------------------------------------------------


def test_rejections_never_touch_orchestrator_or_breaker(tmp_path):
    """Acceptance (e): every rejection happens AT admission — the
    scheduler is never even started here, the breaker never trips, and
    each rejection carries a structured error body + its counter."""
    ctx, orch, srv = make_stack(tmp_path, read_timeout_s=0.75)
    srv.start()
    port = srv.port
    try:
        # 401: missing and unknown tokens.
        s, d = req(port, "POST", "/v1/jobs", {"sbox": "x"}, token=None)
        assert s == 401 and d["error"]["code"] == "unauthorized"
        s, d = post_job(port, toy_sbox_text(0), token="wrong")
        assert s == 401
        # 403: valid token, disabled tenant.
        s, d = post_job(port, toy_sbox_text(0), token="tok-off")
        assert s == 403 and d["error"]["code"] == "forbidden"
        # 429 rate: the slow tenant's bucket holds exactly one draw.
        s, _ = req(port, "GET", "/v1/jobs/net-00", token="tok-slow")
        assert s == 404  # authenticated, consumed the only token
        s, d = req(port, "GET", "/v1/jobs/net-00", token="tok-slow")
        assert s == 429 and d["error"]["code"] == "rate_limited"
        # 429 quota: bob may hold ONE active job.  (A different OUTPUT
        # bit is a genuinely different query — the toy boxes are
        # complement-equivalent on output 0, which the canonical key
        # correctly dedups.)
        s, d = post_job(port, toy_sbox_text(0), token="tok-bob")
        assert s == 202
        s, d = post_job(port, toy_sbox_text(0), output=1, token="tok-bob")
        assert s == 429 and d["error"]["code"] == "over_quota"
        # 413: an oversize body is refused before a byte is read.
        s, d = req(port, "POST", "/v1/jobs", "x" * (65 * 1024))
        assert s == 413 and d["error"]["code"] == "payload_too_large"
        # 411: no Content-Length at all (raw socket — http.client
        # always fills one in for POST).
        c = socket.create_connection(("127.0.0.1", port), timeout=10)
        c.sendall(
            b"POST /v1/jobs HTTP/1.1\r\nHost: t\r\n"
            b"Authorization: Bearer tok-acme\r\n\r\n"
        )
        assert b"411" in c.recv(4096).split(b"\r\n", 1)[0]
        c.close()
        # 400: bad JSON, bad table.
        s, d = req(port, "POST", "/v1/jobs", "{not json")
        assert s == 400
        s, d = post_job(port, "zz not hex")
        assert s == 400 and d["error"]["code"] == "bad_sbox"
        # 408: a slowloris body (headers sent, body stalled) is cut
        # off at the socket read timeout — the serve loop survives.
        c = socket.create_connection(("127.0.0.1", port), timeout=10)
        c.sendall(
            b"POST /v1/jobs HTTP/1.1\r\nHost: t\r\n"
            b"Authorization: Bearer tok-acme\r\n"
            b"Content-Length: 500\r\n\r\npartial"
        )
        first_line = c.recv(4096).split(b"\r\n", 1)[0]
        c.close()
        assert b"408" in first_line
        # The loop is not wedged: a well-formed request still answers.
        s, _ = req(port, "GET", "/v1/jobs/net-00")
        assert s == 404

        # The admission ledger: ONE job admitted (bob's), nothing ran,
        # the shared breaker untouched.
        view = orch.status_view()
        assert view["counts"]["queued"] == 1
        assert view["counts"]["running"] == 0
        assert int(ctx.stats.get("circuit_breaker_trips", 0)) == 0
        assert int(ctx.stats.get("device_dispatches", 0)) == 0
        for name in ("net_rejected_auth", "net_rejected_rate",
                     "net_rejected_quota", "net_oversize",
                     "net_timeouts"):
            assert int(ctx.stats.get(name, 0)) >= 1, name
        assert ctx.stats.undeclared() == set()
    finally:
        srv.close()


# -------------------------------------------------------------------------
# chaos: the four net.* sites + @tenant: targeting
# -------------------------------------------------------------------------


def test_net_chaos_sites_reject_one_request_and_survive(tmp_path):
    """An armed raise at net.accept / net.auth / net.body answers 503
    for THAT request only; the very next request is served normally
    (the serve loop survives every armed site)."""
    ctx, orch, srv = make_stack(tmp_path)
    srv.start()
    port = srv.port
    try:
        for site in ("net.accept", "net.auth", "net.body"):
            faults.arm(site, "raise", "1")
            s, d = post_job(port, toy_sbox_text(0))
            assert s == 503, (site, s, d)
            assert d["error"]["code"] == "unavailable"
            faults.disarm(site)
            s, _ = req(port, "GET", "/v1/jobs/net-00")
            assert s == 404, site  # loop alive, auth path alive
        assert int(ctx.stats.get("net_errors", 0)) == 3
        # Nothing was admitted through three failed POSTs.
        assert orch.status_view()["counts"]["queued"] == 0
    finally:
        srv.close()


def test_admit_journal_fault_is_retryable_on_idempotency_key(tmp_path):
    """An injected net.admit_journal fault after the record lands is a
    503; the client's retry on the SAME Idempotency-Key dedups into
    one job — never a duplicate search, never a lost admission."""
    ctx, orch, srv = make_stack(tmp_path)
    srv.start()
    orch.start()
    port = srv.port
    try:
        faults.arm("net.admit_journal", "raise", "1")
        s, d = post_job(port, toy_sbox_text(0), idem="retry-me")
        assert s == 503 and "retry" in d["error"]["message"]
        faults.disarm("net.admit_journal")
        s, d = post_job(port, toy_sbox_text(0), idem="retry-me")
        assert s in (200, 202)
        jid = d["job_id"]
        # Two admit records (the faulted one was already durable), ONE
        # job: replay dedups on the first record.
        recs = AdmissionJournal.load(orch.root)
        admits = [r for r in recs if r["type"] == "admit"]
        assert [r["job_id"] for r in admits] == [jid, jid]
        assert orch.job(jid) is not None
        s, d = req(port, "GET", f"/v1/jobs/{jid}?wait=60")
        assert s == 200 and d["state"] == "done"
        assert wait_no_pending(orch.root) == []
    finally:
        srv.close()
        orch.run_until_idle(timeout_s=60)
        orch.stop()


def test_tenant_targeting_pin_and_env(monkeypatch):
    """`@tenant:NAME` targeting: an armed tenant-scoped site fires only
    on threads pinned to that tenant (or matching the SBG_FAULT_TENANT
    env fallback), and the spec parser round-trips the form."""
    spec = faults.parse_spec("search.node@tenant:acme:raise@1+")
    assert "search.node@tenant:acme" in spec
    faults.arm("search.node@tenant:acme", "raise", "1+")
    # Unpinned thread: silent.
    faults.fault_point("search.node")
    # Pinned to another tenant: silent.
    faults.set_tenant("blue")
    faults.fault_point("search.node")
    # Pinned to the target: fires.
    faults.set_tenant("acme")
    with pytest.raises(faults.InjectedFault):
        faults.fault_point("search.node")
    # Env fallback covers unpinned threads (workers of a subprocess).
    faults.set_tenant(None)
    monkeypatch.setenv("SBG_FAULT_TENANT", "acme")
    with pytest.raises(faults.InjectedFault):
        faults.fault_point("search.node")
    monkeypatch.delenv("SBG_FAULT_TENANT")
    with pytest.raises(ValueError):
        faults.parse_spec("search.node@tenant:")


# -------------------------------------------------------------------------
# durability: crash between journal append and enqueue; drain + restart
# -------------------------------------------------------------------------

_PHASE_PRELUDE = """
import json, os, sys
sys.path.insert(0, {repo!r})
from sboxgates_tpu.search import Options, SearchContext
from sboxgates_tpu.search.serve import ServeOrchestrator
from sboxgates_tpu.resilience.deadline import DeadlineConfig
from sboxgates_tpu.serve_net import TokenStore, write_token_file
from sboxgates_tpu.serve_net.server import AdmissionServer
DEVOPTS = dict(seed=11, lut_graph=True, randomize=False,
               host_small_steps=False, native_engine=False, warmup=False)
root = {root!r}
tok = os.path.join(root, "..", "tokens.json")
if not os.path.exists(tok):
    write_token_file(tok, {{"acme": {{"token": "t", "rate_per_s": 500,
                                      "burst": 50}}}})
ctx = SearchContext(Options(**DEVOPTS))
orch = ServeOrchestrator(ctx, root, lanes=2,
                         deadline=DeadlineConfig(retries=2,
                                                 backoff_s=0.01),
                         log=lambda s: None)
srv = AdmissionServer(orch, TokenStore.load(tok), ctx.stats, root,
                      log=lambda s: None)
"""

_PHASE1 = _PHASE_PRELUDE + """
srv.start()
import http.client
c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
body = json.dumps({{"sbox": {sbox!r}, "output": 0}})
try:
    c.request("POST", "/v1/jobs", body=body,
              headers={{"Authorization": "Bearer t"}})
    c.getresponse().read()
except Exception:
    pass  # the injected crash kills the process mid-response
print("PHASE1-SURVIVED")  # only reached if the crash did NOT fire
"""

_PHASE2 = _PHASE_PRELUDE + """
replayed = srv.replay()
print("REPLAYED", len(replayed))
orch.start()
view = orch.run_until_idle(timeout_s=120)
orch.stop()
states = sorted(
    (j, row["state"]) for j, row in view["jobs"].items()
)
print("STATES", json.dumps(states))
files = orch.result_files(replayed[0]) if replayed else []
print("RESULTS", len(files))
"""


def _run_phase(script, tmp_path, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", SBG_WARMUP="0")
    env.pop("SBG_FAULTS", None)
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-c", script], cwd=str(tmp_path),
        env=env, capture_output=True, text=True, timeout=300,
    )


def test_crash_between_admit_journal_and_enqueue_replays(tmp_path):
    """Acceptance (c): an ``os._exit`` kill BETWEEN the admission-
    journal append and the orchestrator enqueue (the armed
    net.admit_journal crash window) loses nothing — the record is
    already durable, and the restarted process replays it into the
    orchestrator, runs the job, and completes it exactly once."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = str(tmp_path / "serve")
    os.makedirs(root, exist_ok=True)
    fmt = dict(repo=repo, root=root, sbox=toy_sbox_text(0))

    p1 = _run_phase(
        _PHASE1.format(**fmt), tmp_path,
        env_extra={"SBG_FAULTS": "net.admit_journal:crash@1"},
    )
    assert p1.returncode == 17, (p1.returncode, p1.stdout, p1.stderr)
    assert "PHASE1-SURVIVED" not in p1.stdout
    # The admission survived the kill: journaled, not yet enqueued.
    pend = pending_jobs(root)
    assert len(pend) == 1 and pend[0].startswith("net-")

    p2 = _run_phase(_PHASE2.format(**fmt), tmp_path)
    assert p2.returncode == 0, (p2.stdout, p2.stderr)
    assert "REPLAYED 1" in p2.stdout
    assert '"done"' in p2.stdout and "RESULTS 1" in p2.stdout, p2.stdout
    # Exactly once: the replayed completion is marked, nothing pending.
    assert pending_jobs(root) == []


def test_drain_preserves_admissions_and_restart_resumes(tmp_path):
    """Acceptance (d): the SIGTERM drain order (listener closed FIRST,
    then the orchestrator drained) rejects new work with 503, loses no
    admitted job, and the next boot's replay re-serves every
    unfinished job to completion."""
    ctx, orch, srv = make_stack(tmp_path, "serve")
    srv.start()
    # Scheduler NOT started: admitted jobs stay queued, so the drain
    # deterministically catches them mid-load.
    s, d = post_job(srv.port, toy_sbox_text(0), idem="d0")
    assert s == 202
    s, d2 = post_job(srv.port, toy_sbox_text(1), idem="d1")
    assert s == 202
    admitted = {d["job_id"], d2["job_id"]}
    port = srv.port

    # The CLI's SIGTERM hook order: close the front door, then drain.
    srv.close()
    orch.drain(timeout_s=10.0)
    with pytest.raises(OSError):
        socket.create_connection(("127.0.0.1", port), timeout=2)
    assert set(pending_jobs(orch.root)) == admitted

    # Next boot: same root, fresh context/orchestrator; replay happens
    # BEFORE the listener opens, then the jobs run to completion.
    ctx2, orch2, srv2 = make_stack(tmp_path, "serve")
    replayed = srv2.replay()
    assert set(replayed) == admitted
    srv2.start()
    orch2.start()
    try:
        for jid in sorted(admitted):
            s, d = req(srv2.port, "GET", f"/v1/jobs/{jid}?wait=60")
            assert s == 200 and d["state"] == "done", d
            assert d["circuits"]
        assert wait_no_pending(orch2.root) == []
    finally:
        srv2.close()
        orch2.run_until_idle(timeout_s=60)
        orch2.stop()


# -------------------------------------------------------------------------
# the hardened StatusServer substrate
# -------------------------------------------------------------------------


def test_status_server_survives_half_open_socket():
    """A half-open client (connects, sends nothing) must not wedge the
    single-threaded /status loop: the per-connection timeout cuts it
    off and a real request still answers."""
    reg = tmetrics.context_registry()
    srv = tstatus.StatusServer(reg, port=0, request_timeout_s=0.5)
    srv.start()
    try:
        # Half-open: connect and go silent.
        half = socket.create_connection(("127.0.0.1", srv.port))
        time.sleep(0.1)
        # A well-formed request queued behind it still completes once
        # the stdlib times the silent connection out.
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        c.request("GET", "/status")
        r = c.getresponse()
        doc = json.loads(r.read().decode())
        assert r.status == 200 and "counters" in doc
        c.close()
        half.close()
    finally:
        srv.shutdown()


def test_status_server_bounds_request_size():
    reg = tmetrics.context_registry()
    srv = tstatus.StatusServer(reg, port=0, request_timeout_s=2.0)
    srv.start()
    try:
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        c.request("GET", "/status", headers={"Content-Length": "999999"})
        assert c.getresponse().status == 413
        c.close()
    finally:
        srv.shutdown()


# -------------------------------------------------------------------------
# the token file: fail-closed, durable, permission-checked
# -------------------------------------------------------------------------


def test_token_file_fail_closed(tmp_path):
    path = str(tmp_path / "tokens.json")
    # Missing / corrupt / schema-broken all refuse with one error type.
    with pytest.raises(TokenFileError):
        TokenStore.load(path)
    for bad in (
        "{torn",
        json.dumps({"version": 99, "tenants": {}}),
        json.dumps({"version": 1, "tenants": {}}),
        json.dumps({"version": 1, "tenants": {"a": {}}}),
        json.dumps({"version": 1,
                    "tenants": {"a": {"token": "t", "max_jobs": 0}}}),
    ):
        with open(path, "w") as f:
            f.write(bad)
        os.chmod(path, 0o600)
        with pytest.raises(TokenFileError):
            TokenStore.load(path)
    # The durable writer produces a loadable, owner-only file.
    write_token_file(path, {"a": {"token": "t"}})
    assert (os.stat(path).st_mode & 0o777) == 0o600
    store = TokenStore.load(path)
    assert store.authenticate("Bearer t").name == "a"
    # World-writable credentials are refused statically.
    os.chmod(path, 0o606)
    assert "world-writable" in (check_file(path) or "")
    os.chmod(path, 0o600)
    assert check_file(path) is None
