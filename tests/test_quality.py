"""Solution-quality parity vs the reference's showcased example.

The reference README's one concrete quality figure is a 19-gate circuit
for DES S1 output bit 0 (9 XOR, 4 AND, 3 OR, 3 NOT_A_AND_B — reference
des_s1_bit0.svg, shown at README.md:33-34).  This framework's search
finds a 17-gate circuit for the same target with the same gate family
(gate-availability bitfield 214 = AND | ANDNOT both forms | XOR | OR).
Both the committed artifact and its deterministic reproduction are
checked, so the claim stays verifiable at head.
"""

import os

import numpy as np

from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import NO_GATE, State
from sboxgates_tpu.graph.xmlio import load_state
from sboxgates_tpu.utils.sbox import load_sbox

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
ARTIFACT = os.path.join(REPO, "examples", "des_s1_bit0_17gates.xml")


def _target_and_mask():
    sbox, n = load_sbox(os.path.join(REPO, "sboxes", "des_s1.txt"))
    assert n == 6
    return np.asarray(tt.target_table(sbox, 0)), np.asarray(tt.mask_table(6))


def test_17_gate_artifact_is_correct_and_beats_reference_example():
    target, mask = _target_and_mask()
    st = load_state(ARTIFACT)
    out = st.outputs[0]
    assert out != NO_GATE
    got = np.asarray(st.tables[out])
    assert np.array_equal(got & mask, target & mask)
    gates = st.num_gates - st.num_inputs
    assert gates == 17  # reference showcase: 19
    # Same gate family as the showcase (no free NOTs, no exotic funcs).
    from sboxgates_tpu.core import boolfunc as bf

    allowed = {bf.AND, bf.A_AND_NOT_B, bf.NOT_A_AND_B, bf.XOR, bf.OR}
    used = {st.gates[i].type for i in range(st.num_inputs, st.num_gates)}
    assert used <= allowed, used


def test_17_gate_circuit_reproduces_from_seed():
    """The artifact is not a lucky one-off: seed 18 under a 24-node
    budget re-derives a 17-gate solution deterministically."""
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.kwan import create_circuit

    target, mask = _target_and_mask()
    st = State.init_inputs(6)
    st.max_gates = 24
    ctx = SearchContext(Options(seed=18, avail_gates_bitfield=214))
    out = create_circuit(ctx, st, target, mask, [])
    assert out != NO_GATE
    assert st.num_gates - st.num_inputs == 17
    got = np.asarray(st.tables[out])
    assert np.array_equal(got & mask, target & mask)


# -- round 5: the quality TABLE (examples/quality_sweep.py) ---------------
#
# One data point beats an anecdote; a table beats the reference's entire
# published quality story (its README showcases only des_s1_bit0.svg).
# Every committed row must (a) be a correct circuit for its target and
# (b) re-derive deterministically from its recorded (seed, budget,
# gate family).

import json

import pytest

TABLE_PATH = os.path.join(REPO, "examples", "quality_table.json")


def _table_rows():
    if not os.path.exists(TABLE_PATH):
        return []
    with open(TABLE_PATH) as f:
        return json.load(f)


def _row_target(row):
    sbox, n = load_sbox(os.path.join(REPO, "sboxes", row["sbox"]))
    target = np.asarray(tt.target_table(sbox, row["bit"]))
    return n, target, np.asarray(tt.mask_table(n))


@pytest.mark.parametrize(
    "row", _table_rows(), ids=lambda r: r["target"]
)
def test_quality_table_artifact_is_correct(row):
    n, target, mask = _row_target(row)
    st = load_state(os.path.join(REPO, "examples", row["artifact"]))
    out = st.outputs[row["bit"]]
    assert out != NO_GATE
    got = np.asarray(st.tables[out])
    assert np.array_equal(got & mask, target & mask)
    assert st.num_gates - st.num_inputs == row["best_gates"]
    # Gate-mode rows: the showcase 2-input family (bitfield 214) plus
    # NOT — Kwan step 2 reuses an existing gate's complement as a NOT
    # gate, which the reference's own gate model includes and counts
    # toward the total (no free inverters).  LUT-mode rows: 3-input
    # LUTs plus the same step-1/2 reuse gates.
    from sboxgates_tpu.core import boolfunc as bf

    allowed = {bf.AND, bf.A_AND_NOT_B, bf.NOT_A_AND_B, bf.XOR, bf.OR,
               bf.NOT}
    if row.get("lut_mode"):
        allowed = allowed | {bf.LUT}
    used = {st.gates[i].type for i in range(st.num_inputs, st.num_gates)}
    assert used <= allowed, used


@pytest.mark.parametrize(
    "row", _table_rows(), ids=lambda r: r["target"]
)
def test_quality_table_row_reproduces(row):
    """seed + budget + family re-derive the row's gate count."""
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.kwan import create_circuit

    n, target, mask = _row_target(row)
    st = State.init_inputs(n)
    st.max_gates = row["budget"]
    ctx = SearchContext(
        Options(seed=row["best_seed"],
                avail_gates_bitfield=row["gate_family"],
                lut_graph=bool(row.get("lut_mode")))
    )
    out = create_circuit(ctx, st, target, mask, [])
    assert out != NO_GATE
    assert st.num_gates - st.num_inputs == row["best_gates"]
    got = np.asarray(st.tables[out])
    assert np.array_equal(got & mask, target & mask)


def test_quality_table_exists():
    """The committed table must be present and cover at least the
    advertised core targets (4 DES S1 outputs + 3 crypto1 filters;
    further rows — e.g. DES S2-S8 — are additive)."""
    rows = {r["target"] for r in _table_rows()}
    need = {
        "des_s1_bit0", "des_s1_bit1", "des_s1_bit2", "des_s1_bit3",
        "crypto1_fa", "crypto1_fb", "crypto1_fc",
    }
    assert need <= rows, f"missing rows: {need - rows}"
