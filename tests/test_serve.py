"""Serve-mode orchestrator tests: multi-tenant scheduling over one
shared warm context, chaos-verified bit-exact recovery, poison-job
quarantine isolation, priority preemption, and graceful drain.

The chaos matrix is the acceptance gate: a randomized (seeded)
preempt/kill/requeue schedule over an 8-job serve run must yield final
circuits bit-identical to each job run standalone with the same seed —
the PR 3/7 exact-resume contract, exercised live through the serve
sites.  All tests are in-process (no subprocess per case) and run on
small one-output searches, so the file stays tier-1-cheap.
"""

import hashlib
import json
import os
import time

import numpy as np
import pytest

from sboxgates_tpu.graph.state import State
from sboxgates_tpu.resilience import faults
from sboxgates_tpu.resilience.deadline import DeadlineConfig
from sboxgates_tpu.search import Options, SearchContext
from sboxgates_tpu.search.orchestrator import (
    generate_graph_one_output,
    make_targets,
)
from sboxgates_tpu.search.serve import (
    DONE,
    QUARANTINED,
    QUEUED,
    RUNNING,
    JobView,
    ServeClosed,
    ServeJob,
    ServeOrchestrator,
    job_seed,
    lane_bucket,
)
from sboxgates_tpu.utils.sbox import load_sbox

DATA = os.path.join(os.path.dirname(__file__), "data")
DES = os.path.join(DATA, "des_s1.txt")
FA = os.path.join(DATA, "crypto1_fa.txt")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.disarm()
    yield
    faults.disarm()


def xml_digests(d):
    """{filename: sha256} of every checkpoint under a job directory."""
    return {
        f: hashlib.sha256(
            open(os.path.join(d, f), "rb").read()
        ).hexdigest()
        for f in sorted(os.listdir(d))
        if f.endswith(".xml")
    }


def standalone_digests(tmp_dir, sbox_path, output, seed, iterations=1):
    """The bit-identity reference: the same job run on a FRESH context
    with the same seed and options, no orchestrator anywhere near it."""
    ctx = SearchContext(Options(seed=seed, iterations=iterations))
    sbox, num_inputs = load_sbox(sbox_path, 0)
    targets = make_targets(sbox)
    st = State.init_inputs(num_inputs)
    os.makedirs(tmp_dir, exist_ok=True)
    generate_graph_one_output(
        ctx, st, targets, output, save_dir=tmp_dir,
        log=lambda s: None, journal=None,
    )
    return xml_digests(tmp_dir)


def make_orch(tmp_path, iterations=1, lanes=2, retries=2, seed=11,
              timeout_s=0.0, backoff_s=0.01):
    ctx = SearchContext(Options(seed=seed, iterations=iterations))
    root = str(tmp_path / "serve")
    orch = ServeOrchestrator(
        ctx, root, lanes=lanes,
        deadline=DeadlineConfig(
            budget_s=timeout_s, retries=retries, backoff_s=backoff_s
        ),
        log=lambda s: None,
    )
    return ctx, orch


JOB_SET = [
    # (job_id, sbox, output, tenant, priority)
    ("j0", DES, 0, "acme", 0),
    ("j1", DES, 1, "acme", 0),
    ("j2", DES, 2, "blue", 0),
    ("j3", DES, 3, "blue", 0),
    ("j4", FA, 0, "core", 0),
    ("j5", DES, 0, "core", 0),
    ("j6", DES, 1, "blue", 0),
    ("j7", FA, 0, "acme", 0),
]


def submit_all(orch, jobs=JOB_SET):
    out = []
    for job_id, path, output, tenant, prio in jobs:
        out.append(orch.submit(ServeJob(
            job_id=job_id, sbox_path=path, output=output,
            tenant=tenant, priority=prio,
        )))
    return out


def test_serve_runs_jobs_on_shared_context(tmp_path):
    """Happy path: tenants share one warm context, every job lands DONE
    with per-job artifacts, and the serving metrics fill in."""
    ctx, orch = make_orch(tmp_path, lanes=2)
    submit_all(orch, JOB_SET[:4])
    orch.start()
    view = orch.run_until_idle(timeout_s=120)
    orch.stop()
    assert view["counts"][DONE] == 4, view
    for jid in ("j0", "j1", "j2", "j3"):
        d = os.path.join(orch.root, jid)
        names = os.listdir(d)
        assert "metrics.json" in names
        assert "telemetry.jsonl" in names
        assert "search.journal.jsonl" in names
        assert any(n.endswith(".xml") for n in names)
        # Per-job metrics.json is the job's OWN fork snapshot.
        snap = json.load(open(os.path.join(d, "metrics.json")))
        assert snap["config"]["job"] == jid
    s = ctx.stats
    assert s["serve_jobs_admitted"] == 4
    assert s.get("serve_quarantined", 0) == 0
    hists = s.histograms()
    assert hists["serve_queue_wait_s"]["count"] == 4
    assert hists["job_time_to_first_hit_s"]["count"] == 4
    assert hists["job_seconds"]["count"] == 4
    assert s.undeclared() == set()
    # The run journal the CLI writes is orthogonal; each job journaled.
    rec = json.load(open(os.path.join(
        orch.root, "j0", "search.journal.json")))
    assert rec["records"][0]["type"] == "run_start"


def test_chaos_matrix_bit_identical(tmp_path):
    """THE acceptance gate: a randomized preempt/kill/requeue schedule
    over an 8-job serve run yields final circuits bit-identical to each
    job run standalone.  The schedule is seeded (reproducible) and
    drives all three chaos shapes through the standard injection
    machinery: ``serve.preempt@job:ID`` (preemption at a journal
    boundary), ``search.node@job:ID`` (a mid-iteration kill whose retry
    resumes from the journal), and a global ``serve.requeue`` raise (a
    chaos-lost requeue that consumes a retry instead of losing the
    job)."""
    rng = np.random.default_rng(42)
    ctx, orch = make_orch(tmp_path, iterations=2, lanes=3, retries=4)
    jobs = submit_all(orch)
    # Randomized schedule: 3 preempt victims, 2 kill victims (disjoint
    # draws may overlap — a job may be both preempted AND killed).
    victims = rng.choice([j.job_id for j in jobs], size=3, replace=False)
    for v in victims:
        faults.arm(f"serve.preempt@job:{v}", "raise",
                   str(int(rng.integers(1, 3))))
    kills = rng.choice([j.job_id for j in jobs], size=2, replace=False)
    for v in kills:
        faults.arm(f"search.node@job:{v}", "raise",
                   str(int(rng.integers(1, 4))))
    faults.arm("serve.requeue", "raise", "2")
    orch.start()
    view = orch.run_until_idle(timeout_s=240)
    orch.stop()
    assert view["counts"][DONE] == len(jobs), view
    assert ctx.stats["serve_preemptions"] >= 1
    # Bit-identity: every job's final checkpoints equal its standalone
    # run's, chaos or no chaos.
    for j in jobs:
        ref = standalone_digests(
            str(tmp_path / f"ref-{j.job_id}"), j.sbox_path, j.output,
            int(j.seed), iterations=2,
        )
        got = xml_digests(os.path.join(orch.root, j.job_id))
        assert got == ref, f"{j.job_id} diverged under chaos"
    assert ctx.stats.undeclared() == set()


def test_poison_job_quarantined_healthy_tenants_unaffected(tmp_path):
    """A job that fails every attempt exhausts its retry schedule and
    is quarantined — without tripping the shared device breaker,
    stalling the queue, or perturbing its neighbors' results."""
    ctx, orch = make_orch(tmp_path, lanes=2, retries=1)
    jobs = submit_all(orch, JOB_SET[:3])
    poison = orch.submit(ServeJob(
        job_id="poison", sbox_path=DES, output=0, tenant="evil",
    ))
    faults.arm("search.node@job:poison", "raise", "1+")
    orch.start()
    view = orch.run_until_idle(timeout_s=120)
    orch.stop()
    assert view["jobs"]["poison"]["state"] == QUARANTINED
    assert view["counts"][QUARANTINED] == 1
    assert view["counts"][DONE] == 3
    assert ctx.stats["serve_quarantined"] == 1
    assert poison.failures == 2  # initial attempt + 1 retry
    # Isolation: the shared context is untouched by the poison tenant.
    assert ctx.device_degraded is False
    # The quarantine left a post-mortem in the poison job's own dir.
    pdir = os.path.join(orch.root, "poison")
    assert any(n.startswith("flight-") for n in os.listdir(pdir)), (
        os.listdir(pdir)
    )
    # Healthy tenants' circuits are bit-identical to standalone runs.
    for j in jobs:
        ref = standalone_digests(
            str(tmp_path / f"ref-{j.job_id}"), j.sbox_path, j.output,
            int(j.seed),
        )
        assert xml_digests(os.path.join(orch.root, j.job_id)) == ref


def _wait_state(orch, job_id, state, timeout_s=30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if orch.status_view()["jobs"][job_id]["state"] == state:
            return True
        time.sleep(0.01)
    return False


def test_priority_preemption_resumes_bit_identical(tmp_path):
    """A higher-priority arrival preempts the lowest-priority running
    job when no lane is free; the victim's snapshot+requeue resume is
    bit-exact."""
    ctx, orch = make_orch(tmp_path, iterations=4, lanes=1, retries=2)
    low = orch.submit(ServeJob(
        job_id="low", sbox_path=DES, output=0, tenant="t", priority=0,
    ))
    orch.start()
    assert _wait_state(orch, "low", RUNNING)
    high = orch.submit(ServeJob(
        job_id="high", sbox_path=FA, output=0, tenant="t", priority=5,
    ))
    view = orch.run_until_idle(timeout_s=120)
    orch.stop()
    assert view["counts"][DONE] == 2, view
    # The preemption actually happened (the victim has >= 1 iteration
    # per attempt, so the boundary lands while high waits).
    assert low.preemptions >= 1
    assert ctx.stats["serve_preemptions"] >= 1
    for j, iters in ((low, 4), (high, 4)):
        ref = standalone_digests(
            str(tmp_path / f"ref-{j.job_id}"), j.sbox_path, j.output,
            int(j.seed), iterations=iters,
        )
        assert xml_digests(os.path.join(orch.root, j.job_id)) == ref


def test_drain_snapshots_requeues_and_recovers(tmp_path):
    """drain(): admission closes, running jobs preempt at their next
    journal boundary with per-job artifacts (final heartbeat +
    metrics.json + flight dump), and a NEW orchestrator over the same
    root finishes every job bit-identically."""
    ctx, orch = make_orch(tmp_path, iterations=3, lanes=2)
    jobs = submit_all(orch, JOB_SET[:3])
    orch.start()
    assert _wait_state(orch, "j0", RUNNING)
    view = orch.drain(timeout_s=30)
    assert view["draining"]
    assert all(
        r["state"] in (QUEUED, DONE) for r in view["jobs"].values()
    ), view
    with pytest.raises(ServeClosed):
        orch.submit(ServeJob(job_id="late", sbox_path=DES, output=0))
    preempted = [
        jid for jid, r in view["jobs"].items()
        if r["state"] == QUEUED and r.get("preemptions", 0) > 0
    ]
    assert preempted, view  # at least one job was mid-flight
    for jid in preempted:
        d = os.path.join(orch.root, jid)
        names = os.listdir(d)
        assert "metrics.json" in names, names
        assert any(n.startswith("flight-") for n in names), names
        lines = [json.loads(line) for line in
                 open(os.path.join(d, "telemetry.jsonl"))]
        assert lines[-1]["kind"] == "final"
    # Recovery: a fresh orchestrator (same root, same seeds) completes
    # the preempted jobs from their journals.
    ctx2 = SearchContext(Options(seed=11, iterations=3))
    orch2 = ServeOrchestrator(
        ctx2, orch.root, lanes=2,
        deadline=DeadlineConfig(retries=2, backoff_s=0.01),
        log=lambda s: None,
    )
    for j in jobs:
        orch2.submit(ServeJob(
            job_id=j.job_id, sbox_path=j.sbox_path, output=j.output,
            tenant=j.tenant, seed=j.seed,
        ))
    orch2.start()
    view2 = orch2.run_until_idle(timeout_s=120)
    orch2.stop()
    assert view2["counts"][DONE] == 3, view2
    for j in jobs:
        ref = standalone_digests(
            str(tmp_path / f"ref-{j.job_id}"), j.sbox_path, j.output,
            int(j.seed), iterations=3,
        )
        assert xml_digests(os.path.join(orch.root, j.job_id)) == ref


def test_job_timeout_rides_deadline_machinery(tmp_path):
    """A per-attempt wall budget of ~0 breaches at the first journal
    boundary (DispatchTimeout, the deadline machinery's exception),
    consumes the retry schedule, and quarantines — all without touching
    neighbors."""
    ctx, orch = make_orch(
        tmp_path, iterations=2, lanes=2, retries=1, timeout_s=1e-9
    )
    orch.submit(ServeJob(job_id="slow", sbox_path=DES, output=0))
    orch.start()
    view = orch.run_until_idle(timeout_s=60)
    orch.stop()
    assert view["jobs"]["slow"]["state"] == QUARANTINED
    assert "DispatchTimeout" in view["jobs"]["slow"]["error"]


def test_admission_fair_share_and_bucket_grouping(tmp_path):
    """The bin-packing pick: priority first, warm-bucket affinity next,
    then fair-share tenant rotation (fewest running lanes first) with
    FIFO as the tiebreak."""
    ctx, orch = make_orch(tmp_path, lanes=2)
    # Not started: exercise the pick directly, under the lock protocol.
    a0 = orch.submit(ServeJob(job_id="a0", sbox_path=DES, tenant="a"))
    a1 = orch.submit(ServeJob(job_id="a1", sbox_path=DES, tenant="a"))
    b0 = orch.submit(ServeJob(job_id="b0", sbox_path=DES, tenant="b"))
    hi = orch.submit(ServeJob(
        job_id="hi", sbox_path=DES, tenant="c", priority=9,
    ))
    now = time.perf_counter()
    with orch._cv:
        picks = orch._admit_locked(now)
    # Priority wins lane 1; fair share gives lane 2 to the earliest
    # job of a fresh tenant rather than a's second job.
    assert [j.job_id for j in picks] == ["hi", "a0"]
    del a1, b0
    # Bucket affinity: with a wave running at bucket 64, a same-bucket
    # later submission beats an earlier-submitted bigger-bucket job —
    # warm-kernel grouping ACROSS tenants outranks tenant rotation.
    ctx2 = SearchContext(Options(seed=1))
    orch2 = ServeOrchestrator(
        ctx2, str(tmp_path / "s2"), lanes=2,
        deadline=DeadlineConfig(), log=lambda s: None,
    )
    r0 = orch2.submit(ServeJob(job_id="r0", sbox_path=DES, tenant="a"))
    cold = orch2.submit(ServeJob(job_id="cold", sbox_path=DES,
                                 tenant="b"))
    warm = orch2.submit(ServeJob(job_id="warm", sbox_path=DES,
                                 tenant="a"))
    cold.bucket = 512
    with orch2._cv:
        r0.state = RUNNING  # one lane busy at bucket 64
        more = orch2._admit_locked(time.perf_counter())
    assert [j.job_id for j in more] == ["warm"]


def test_wave_affinity_pulls_mates_without_starving_fifo(tmp_path):
    """Wave re-group affinity is a PULL, not a penalty: a former wave
    member keeps its FIFO position against fresh jobs, and once one
    member is picked on merit its recorded wave-mates follow into the
    same admission round ahead of later fresh submissions."""
    ctx, orch = make_orch(tmp_path, lanes=3)
    w1 = orch.submit(ServeJob(job_id="w1", sbox_path=DES, tenant="a"))
    f1 = orch.submit(ServeJob(job_id="f1", sbox_path=DES, tenant="b"))
    w2 = orch.submit(ServeJob(job_id="w2", sbox_path=DES, tenant="c"))
    f2 = orch.submit(ServeJob(job_id="f2", sbox_path=DES, tenant="d"))
    w1.last_wave = w2.last_wave = "w1,w2"
    with orch._cv:
        picks = orch._admit_locked(time.perf_counter())
    # w1 leads by FIFO (its wave history is no handicap), w2 is pulled
    # in by affinity ahead of the earlier-submitted f1.
    assert [j.job_id for j in picks] == ["w1", "w2", "f1"]
    del f2


def test_requeued_job_not_readmitted_until_worker_lands(tmp_path):
    """_requeue flips a job back to QUEUED from the worker's except
    block, BEFORE its finally writes artifacts and pops the worker
    entry — admission must skip the job while its previous worker is
    still registered, or two workers race on one job directory."""
    ctx, orch = make_orch(tmp_path, lanes=2)
    j = orch.submit(ServeJob(job_id="jq", sbox_path=DES, output=0))
    now = time.perf_counter()
    with orch._cv:
        orch._workers["jq"] = object()  # previous attempt still landing
        assert orch._admit_locked(now) == []
        orch._workers.pop("jq")
        assert orch._admit_locked(now) == [j]


def test_preempt_targets_skip_already_flagged_victims(tmp_path):
    """A victim whose preemption is already in flight must not shadow
    the next-lowest-priority lane from a second higher-priority
    waiter."""
    ctx, orch = make_orch(tmp_path, lanes=2)
    a = orch.submit(ServeJob(job_id="a", sbox_path=DES, priority=0))
    b = orch.submit(ServeJob(job_id="b", sbox_path=DES, priority=0))
    x = orch.submit(ServeJob(job_id="x", sbox_path=DES, priority=5))
    y = orch.submit(ServeJob(job_id="y", sbox_path=DES, priority=5))
    now = time.perf_counter()
    with orch._cv:
        a.state = RUNNING
        b.state = RUNNING
        a._preempt.set()  # X's preemption of A already in flight
        targets = orch._preempt_targets_locked(now)
    assert targets == [b]
    del x, y


def test_serve_helpers_and_closed_queue(tmp_path):
    """job_seed is deterministic and id-sensitive; lane_bucket rounds
    up the fleet ladder; duplicate ids are rejected."""
    assert job_seed(5, "a") == job_seed(5, "a")
    assert job_seed(5, "a") != job_seed(5, "b")
    assert job_seed(6, "a") != job_seed(5, "a")
    assert lane_bucket(1) == 1
    assert lane_bucket(3) == 4
    assert lane_bucket(33) == 64
    assert lane_bucket(10**6) == 4096
    ctx, orch = make_orch(tmp_path)
    orch.submit(ServeJob(job_id="dup", sbox_path=DES))
    with pytest.raises(ValueError):
        orch.submit(ServeJob(job_id="dup", sbox_path=DES))


def test_job_targeted_fault_specs():
    """``@job:ID`` parsing and thread-local targeting: the fault fires
    only on the thread currently running the matching job, each
    variant keeps its own hit counter, and a ':' in a site name stays
    invalid outside the @rank/@job suffixes."""
    import threading

    spec = faults.parse_spec("serve.preempt@job:j-3:raise@2")
    assert "serve.preempt@job:j-3" in spec
    with pytest.raises(ValueError):
        faults.parse_spec("serve:preempt:raise")
    faults.arm("serve.preempt@job:j3", "raise", "1")
    fired = {}

    def run(job, n):
        faults.set_job(job)
        hits = 0
        for _ in range(n):
            try:
                faults.fault_point("serve.preempt")
            except faults.InjectedFault:
                hits += 1
        fired[job] = hits
        faults.set_job(None)

    threads = [
        threading.Thread(target=run, args=(j, 2)) for j in ("j3", "j4")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert fired == {"j3": 1, "j4": 0}
    assert faults.hit_count("serve.preempt@job:j3") == 2
    # No current job and no env fallback: the qualified lookup is
    # skipped entirely (the unarmed plain site stays a no-op).
    faults.fault_point("serve.preempt")


def test_serve_admit_fault_site_is_loud(tmp_path):
    """An injected admission failure raises out of submit() — the job
    is rejected loudly, never half-admitted."""
    ctx, orch = make_orch(tmp_path)
    faults.arm("serve.admit", "raise", "1")
    with pytest.raises(faults.InjectedFault):
        orch.submit(ServeJob(job_id="x", sbox_path=DES))
    assert "x" not in orch.status_view()["jobs"]
    assert ctx.stats.get("serve_jobs_admitted", 0) == 0


def test_status_view_watch_render_and_heartbeat_section(tmp_path):
    """The per-job queue view: schema, counts, per-job ttfh — rendered
    by telemetry.watch and carried on heartbeat lines via the extra
    provider (read from registry forks; no device syncs)."""
    from sboxgates_tpu.telemetry.heartbeat import Heartbeat
    from sboxgates_tpu.telemetry.watch import render, render_serve

    ctx, orch = make_orch(tmp_path, lanes=2)
    submit_all(orch, JOB_SET[:2])
    hb_dir = str(tmp_path / "hb")
    hb = Heartbeat(
        ctx.stats, hb_dir, interval_s=0,
        extra={"serve": orch.status_view},
    ).start()
    orch.start()
    view = orch.run_until_idle(timeout_s=120)
    orch.stop()
    hb.stop()
    assert view["schema"] == 1
    assert view["lane_bucket"] == 2
    for row in view["jobs"].values():
        assert row["state"] == DONE
        assert "ttfh_s" in row
    # watch renders the serve section from a heartbeat record.
    lines = [json.loads(line) for line in
             open(os.path.join(hb_dir, "telemetry.jsonl"))]
    final = lines[-1]
    assert final["serve"]["counts"][DONE] == 2
    text = render(final)
    assert "serve lanes=2" in text
    assert "done=2" in text
    block = "\n".join(render_serve(final["serve"]))
    assert "j0" in block and "tenant=acme" in block


# -------------------------------------------------------------------------
# Fleet-merged serve waves
# -------------------------------------------------------------------------

#: Device-dispatch configuration (mirrors tests/test_fleet.py DEV): node
#: heads dispatch to the (CPU) device instead of routing native, so a
#: merged wave's rendezvous actually merges sweeps.
DEVOPTS = dict(
    seed=11, lut_graph=True, randomize=False, host_small_steps=False,
    native_engine=False, warmup=False,
)


def _toy_sbox_files(tmp_path, n=8):
    """The fleet fixture corpus written as S-box input files (3-input
    searches whose node sweeps make real device dispatches under
    DEVOPTS)."""
    from sboxgates_tpu.search.fleet import toy_fleet_boxes

    d = tmp_path / "boxes"
    os.makedirs(d, exist_ok=True)
    paths = []
    for i, bj in enumerate(toy_fleet_boxes(n)):
        p = str(d / f"toy{i}.txt")
        with open(p, "w") as f:
            f.write(" ".join("%02x" % v for v in bj.sbox[:8]))
        paths.append(p)
    return paths


def make_dev_orch(tmp_path, lanes, retries=2, merge=None, sub="serve",
                  **opts):
    ctx = SearchContext(Options(**{**DEVOPTS, **opts}))
    orch = ServeOrchestrator(
        ctx, str(tmp_path / sub), lanes=lanes,
        deadline=DeadlineConfig(retries=retries, backoff_s=0.01),
        log=lambda s: None, merge=merge,
    )
    return ctx, orch


def dev_standalone_digests(tmp_dir, sbox_path, output, seed, **opts):
    """Bit-identity reference under the device-dispatch configuration."""
    from sboxgates_tpu.search.orchestrator import generate_graph

    ctx = SearchContext(Options(**{**DEVOPTS, **opts, "seed": seed}))
    sbox, num_inputs = load_sbox(sbox_path, 0)
    targets = make_targets(sbox)
    st = State.init_inputs(num_inputs)
    os.makedirs(tmp_dir, exist_ok=True)
    if output >= 0:
        generate_graph_one_output(
            ctx, st, targets, output, save_dir=tmp_dir,
            log=lambda s: None, journal=None,
        )
    else:
        generate_graph(
            ctx, st, targets, save_dir=tmp_dir, log=lambda s: None,
        )
    return xml_digests(tmp_dir)


def test_merged_wave_one_dispatch_per_round_bit_identical(tmp_path):
    """THE tentpole gate: an 8-tenant same-bucket wave's node sweeps
    merge into single fleet dispatches (per-round device dispatches
    ~1 vs ~8 per-thread), and every job's circuits stay byte-identical
    to its standalone run."""
    paths = _toy_sbox_files(tmp_path)
    # Per-thread reference arm (merge off): same jobs, own dispatches.
    ctx_u, orch_u = make_dev_orch(tmp_path, lanes=8, merge=False,
                                  sub="unmerged")
    for i, p in enumerate(paths):
        orch_u.submit(ServeJob(job_id=f"t{i}", sbox_path=p, output=0))
    orch_u.start()
    view_u = orch_u.run_until_idle(timeout_s=240)
    orch_u.stop()
    assert view_u["counts"][DONE] == 8, view_u
    assert ctx_u.stats.get("serve_merged_dispatches", 0) == 0
    unmerged = int(ctx_u.stats["device_dispatches"])

    ctx, orch = make_dev_orch(tmp_path, lanes=8)
    jobs = [
        orch.submit(ServeJob(
            job_id=f"t{i}", sbox_path=p, output=0, tenant=f"ten{i % 3}",
        ))
        for i, p in enumerate(paths)
    ]
    orch.start()
    view = orch.run_until_idle(timeout_s=240)
    orch.stop()
    assert view["counts"][DONE] == 8, view
    s = ctx.stats
    assert s["serve_merged_dispatches"] >= 1
    assert s.histograms()["serve_wave_lanes"]["count"] >= 1
    assert s.histograms()["serve_wave_lanes"]["max"] == 8.0
    # The wave merged: one dispatch serves many lanes' submissions, and
    # the whole run costs at most half the per-thread arm's dispatches
    # (~1/8 when the lanes stay in lockstep).
    assert s["fleet_submits"] > s["serve_merged_dispatches"]
    merged = int(
        s["device_dispatches"]
    )
    assert merged * 2 <= unmerged, (merged, unmerged)
    assert s.undeclared() == set()
    for j in jobs:
        ref = dev_standalone_digests(
            str(tmp_path / f"ref-{j.job_id}"), j.sbox_path, j.output,
            int(j.seed),
        )
        got = xml_digests(os.path.join(orch.root, j.job_id))
        assert got == ref, f"{j.job_id} diverged in the merged wave"


def test_merged_wave_randomized_draw_stream_matches_standalone(tmp_path):
    """Randomized jobs are the draw-stream acid test: the wave
    rendezvous must not change HOW a job consumes its PRNG (seed
    blocks, mux-branch draws — JobView.allow_mux_threads pins the
    standalone shape), so randomize=True merged-wave circuits stay
    byte-identical to standalone runs."""
    paths = _toy_sbox_files(tmp_path, n=4)
    ctx, orch = make_dev_orch(tmp_path, lanes=4, randomize=True)
    jobs = [
        orch.submit(ServeJob(job_id=f"r{i}", sbox_path=p, output=0))
        for i, p in enumerate(paths)
    ]
    orch.start()
    view = orch.run_until_idle(timeout_s=240)
    orch.stop()
    assert view["counts"][DONE] == 4, view
    for j in jobs:
        ref = dev_standalone_digests(
            str(tmp_path / f"ref-{j.job_id}"), j.sbox_path, j.output,
            int(j.seed), randomize=True,
        )
        got = xml_digests(os.path.join(orch.root, j.job_id))
        assert got == ref, f"{j.job_id}: randomized draws diverged"


def test_merged_chaos_matrix_and_poison_lane(tmp_path):
    """The PR 13 chaos gate with the fleet path underneath: randomized
    preempt/kill schedules over an 8-job merged-wave run stay
    bit-identical to standalone digests, and a poison lane quarantines
    without poisoning its wave-mates."""
    rng = np.random.default_rng(42)
    paths = _toy_sbox_files(tmp_path)
    ctx, orch = make_dev_orch(tmp_path, lanes=4, retries=4)
    jobs = [
        orch.submit(ServeJob(
            job_id=f"t{i}", sbox_path=p, output=0, tenant=f"ten{i % 3}",
        ))
        for i, p in enumerate(paths)
    ]
    poison = orch.submit(ServeJob(
        job_id="poison", sbox_path=paths[0], output=0, tenant="evil",
    ))
    victims = rng.choice([j.job_id for j in jobs], size=2, replace=False)
    for v in victims:
        faults.arm(f"serve.preempt@job:{v}", "raise", "1")
    kill = rng.choice([j.job_id for j in jobs], size=1)[0]
    faults.arm(f"search.node@job:{kill}", "raise", "1")
    # The poison lane dies AT WAVE ENTRY on every attempt — the wave
    # fault site itself — so its rendezvous slot must always be
    # released without stranding wave-mates.  (When a scheduling round
    # happens to admit it solo there IS no wave entry, so the
    # search.node arm below keeps it poisonous either way.)
    faults.arm("serve.wave@job:poison", "raise", "1+")
    faults.arm("search.node@job:poison", "raise", "1+")
    orch.start()
    view = orch.run_until_idle(timeout_s=240)
    orch.stop()
    assert view["jobs"]["poison"]["state"] == QUARANTINED, view
    assert view["counts"][DONE] == 8, view
    assert ctx.stats["serve_preemptions"] >= 1
    assert ctx.stats["serve_merged_dispatches"] >= 1
    for j in jobs:
        ref = dev_standalone_digests(
            str(tmp_path / f"ref-{j.job_id}"), j.sbox_path, j.output,
            int(j.seed),
        )
        got = xml_digests(os.path.join(orch.root, j.job_id))
        assert got == ref, f"{j.job_id} diverged under merged chaos"


def test_drain_mid_merged_wave_no_stranded_lanes(tmp_path):
    """The drain regression gate: drain() during an in-flight merged
    wave must not strand the non-preempted lanes — every lane lands
    QUEUED (snapshot at its journal boundary) or DONE, the requeue
    records wave membership in the sidecar, and a resuming orchestrator
    re-groups deterministically and finishes bit-identically.  A chaos
    ``serve.drain`` injection fires mid-wave first: the injected drain
    failure is loud, and the retried drain still cleans up."""
    paths = _toy_sbox_files(tmp_path, n=4)
    ctx, orch = make_dev_orch(tmp_path, lanes=4, iterations=4)
    jobs = [
        orch.submit(ServeJob(job_id=f"t{i}", sbox_path=p, output=0))
        for i, p in enumerate(paths)
    ]
    # One lane preempts at its first journal boundary mid-wave: a
    # deterministic wave requeue (and sidecar row) regardless of how
    # fast the other lanes run.
    faults.arm("serve.preempt@job:t0", "raise", "1")
    faults.arm("serve.drain", "raise", "1")
    orch.start()
    assert _wait_state(orch, "t1", RUNNING) or _wait_state(
        orch, "t0", RUNNING
    )
    with pytest.raises(faults.InjectedFault):
        orch.drain(timeout_s=30)  # chaos-injected drain: loud, no harm
    view = orch.drain(timeout_s=60)
    assert all(
        r["state"] in (QUEUED, DONE) for r in view["jobs"].values()
    ), view
    # The preempted lane's wave membership is durable and carries the
    # full member list.
    waves_path = os.path.join(orch.root, "waves.jsonl")
    assert os.path.exists(waves_path)
    recs = [json.loads(line) for line in open(waves_path)]
    assert any(r["requeued"] == "t0" for r in recs)
    key = next(r["key"] for r in recs if r["requeued"] == "t0")
    assert set(key.split(",")) == {f"t{i}" for i in range(4)}
    # Recovery: a fresh orchestrator re-groups (affinity restored from
    # the sidecar) and completes every job bit-identically.
    ctx2, orch2 = make_dev_orch(
        tmp_path, lanes=4, iterations=4, sub="serve",
    )
    assert orch2._prior_waves.get("t0") == key
    for j in jobs:
        orch2.submit(ServeJob(
            job_id=j.job_id, sbox_path=j.sbox_path, output=j.output,
            seed=j.seed,
        ))
    assert orch2._jobs["t0"].last_wave == key
    orch2.start()
    view2 = orch2.run_until_idle(timeout_s=240)
    orch2.stop()
    assert view2["counts"][DONE] == 4, view2
    for j in jobs:
        ref = dev_standalone_digests(
            str(tmp_path / f"ref-{j.job_id}"), j.sbox_path, j.output,
            int(j.seed), iterations=4,
        )
        got = xml_digests(os.path.join(orch.root, j.job_id))
        assert got == ref, f"{j.job_id} diverged across the drain"


def test_serve_no_merge_env_and_param(tmp_path, monkeypatch):
    """The opt-out lever: merge=False (or SBG_SERVE_NO_MERGE=1) keeps
    per-job dispatch streams — no waves form, results unchanged."""
    ctx, orch = make_dev_orch(tmp_path, lanes=4, merge=False)
    assert orch.merge is False
    monkeypatch.setenv("SBG_SERVE_NO_MERGE", "1")
    ctx2, orch2 = make_dev_orch(tmp_path, lanes=4, sub="s2")
    assert orch2.merge is False
    monkeypatch.delenv("SBG_SERVE_NO_MERGE")
    ctx3, orch3 = make_dev_orch(tmp_path, lanes=1, sub="s3")
    assert orch3.merge is False  # one lane can never form a wave


def test_jobview_isolation(tmp_path):
    """A JobView shares the base's derived tables and caches but owns
    its PRNG and registry fork; its draws never move the base stream."""
    ctx = SearchContext(Options(seed=3))
    before = ctx.rng_snapshot()
    v = JobView(ctx, 1234)
    ref = np.random.default_rng(1234)
    assert v.next_seed() == int(ref.integers(0, 2**31, size=256)[0])
    assert ctx.rng_snapshot() == before
    assert v.pair_table is ctx.pair_table
    assert v._table_cache is ctx._table_cache
    v.stats.inc("lut5_candidates", 7)
    assert ctx.stats.get("lut5_candidates", 0) == 0
    ctx.stats.merge(v.stats)
    assert ctx.stats["lut5_candidates"] == 7
