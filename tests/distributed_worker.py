"""One process of the 2-process distributed smoke test.

Spawned by test_distributed.py: connects into a 2-process CPU runtime (4
virtual devices per process -> 8 global), builds the global search mesh,
and runs the sharded pivot 5-LUT search on a planted decomposition.  Both
processes must print the identical RESULT line.

Usage: distributed_worker.py <process_id> <coordinator_port>
"""

import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), "..", ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from sboxgates_tpu.parallel import distributed as dist  # noqa: E402

dist.initialize(f"127.0.0.1:{port}", 2, pid)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

from planted import build_planted_lut5  # noqa: E402

from sboxgates_tpu.parallel import MeshPlan, make_mesh  # noqa: E402
from sboxgates_tpu.search import Options, SearchContext  # noqa: E402
from sboxgates_tpu.search.lut import lut5_search  # noqa: E402

# Same planted state as test_lut5_pivot_sharded_equals_single.
st, target, mask = build_planted_lut5()

plan = MeshPlan(make_mesh())  # global mesh spanning both processes
ctx = SearchContext(Options(lut_graph=True, randomize=False), mesh_plan=plan)
res = lut5_search(ctx, st, target, mask, [])
assert res is not None, "distributed pivot search found nothing"
print(
    "RESULT %d %d %d %s"
    % (
        pid,
        res["func_outer"],
        res["func_inner"],
        " ".join(str(g) for g in res["gates"]),
    ),
    flush=True,
)
