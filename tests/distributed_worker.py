"""One process of the 2-process distributed smoke test.

Spawned by test_distributed.py: connects into a 2-process CPU runtime (4
virtual devices per process -> 8 global), builds the global search mesh,
and runs the sharded pivot 5-LUT search on a planted decomposition.  Both
processes must print the identical RESULT line.

Usage: distributed_worker.py <process_id> <coordinator_port>
"""

import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), "..", ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from sboxgates_tpu.parallel import distributed as dist  # noqa: E402

dist.initialize(f"127.0.0.1:{port}", 2, pid)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

from planted import build_planted_lut5, build_planted_lut5_small  # noqa: E402

from sboxgates_tpu.parallel import MeshPlan, make_mesh  # noqa: E402
from sboxgates_tpu.search import Options, SearchContext  # noqa: E402
from sboxgates_tpu.search.lut import lut5_search  # noqa: E402

# Same planted state as test_lut5_pivot_sharded_equals_single.
st, target, mask = build_planted_lut5()

plan = MeshPlan(make_mesh())  # global mesh spanning both processes
ctx = SearchContext(Options(lut_graph=True, randomize=False), mesh_plan=plan)
res = lut5_search(ctx, st, target, mask, [])
assert res is not None, "distributed pivot search found nothing"
print(
    "RESULT %d %d %d %s"
    % (
        pid,
        res["func_outer"],
        res["func_inner"],
        " ".join(str(g) for g in res["gates"]),
    ),
    flush=True,
)

# Second leg: the chunked (non-pivot) mesh path, whose multi-host gather is
# the compacted top-K one.  SBG_GATHER_ROWS=1 in the parent environment
# forces the per-device row budget to overflow, exercising the full-gather
# re-drive; both legs must agree across processes either way.
st2, target2, mask2 = build_planted_lut5_small()
ctx2 = SearchContext(Options(lut_graph=True, randomize=False), mesh_plan=plan)
res2 = lut5_search(ctx2, st2, target2, mask2, [])
assert res2 is not None, "distributed chunked search found nothing"
print(
    "RESULT2 %d %d %d %s"
    % (
        pid,
        res2["func_outer"],
        res2["func_inner"],
        " ".join(str(g) for g in res2["gates"]),
    ),
    flush=True,
)

# Completeness proof for the compacted gather: the driver's reconstructed
# dense chunk must equal the full-gather kernel's output row for row.
import numpy as np  # noqa: E402

from sboxgates_tpu.parallel.mesh import sharded_feasible_stream  # noqa: E402

prebuilt = ctx2.stream_args(st2, target2, mask2, [], 5)
base_args, total, chunk0 = prebuilt
n = plan.n_candidate_shards
chunk = -(-chunk0 // n) * n
found, cstart, feas, r1, r0, _, _ = ctx2.feasible_stream_driver(
    st2, target2, mask2, [], k=5, prebuilt=prebuilt
)
assert found, "planted chunk must contain feasible rows"
_, feas_f, r1_f, r0_f = sharded_feasible_stream(
    plan, *base_args, cstart, total, k=5, chunk=chunk, compact=False
)
feas_f, r1_f, r0_f = (np.asarray(x) for x in (feas_f, r1_f, r0_f))
feas, r1, r0 = (np.asarray(x) for x in (feas, r1, r0))
assert (feas == feas_f).all(), "compacted feasibility diverges"
assert (r1[feas_f] == r1_f[feas_f]).all(), "compacted req1 diverges"
assert (r0[feas_f] == r0_f[feas_f]).all(), "compacted req0 diverges"
print("STREAMCHECK %d ok rows=%d" % (pid, int(feas_f.sum())), flush=True)

# Third leg: the full search engine under the multi-host mesh, driving the
# node-head routing agreement (SearchContext._native_all_procs — an
# all-gather every process must join).  The parent may set
# SBG_DISABLE_NATIVE on ONE process to make availability heterogeneous;
# the agreement must then route both processes to the device kernels and
# the searches must still agree bit-for-bit.
from sboxgates_tpu.core import ttable as tt  # noqa: E402
from sboxgates_tpu.graph.state import State  # noqa: E402
from sboxgates_tpu.search import make_targets  # noqa: E402
from sboxgates_tpu.search.kwan import create_circuit  # noqa: E402
from sboxgates_tpu.utils.sbox import load_sbox  # noqa: E402

sbox, n_in = load_sbox(
    os.path.join(os.path.dirname(__file__), "..", "sboxes", "crypto1_fa.txt")
)
ctx3 = SearchContext(
    Options(lut_graph=True, randomize=False, seed=3), mesh_plan=plan
)
st3 = State.init_inputs(n_in)
out = create_circuit(
    ctx3, st3, make_targets(sbox)[0], tt.mask_table(n_in), []
)
assert out != 0xFFFF, "mesh engine search found nothing"
print(
    "ENGINE %d out=%d gates=%d native=%s"
    % (pid, out, st3.num_gates, ctx3.uses_native_step(st3)),
    flush=True,
)

# k=7 compacted-stream shape coverage: the 7-LUT constraints are packed
# as [rows, 4] words (vs scalar words for k<=5); the compact gather and
# dense reconstruction must agree with the full gather for that shape
# too.
from planted import build_planted_lut7  # noqa: E402

st7, target7, mask7 = build_planted_lut7()
ctx7 = SearchContext(
    Options(lut_graph=True, randomize=False), mesh_plan=plan
)
pre7 = ctx7.stream_args(st7, target7, mask7, [], 7)
found7, c7, feas7, r17, r07, _, _ = ctx7.feasible_stream_driver(
    st7, target7, mask7, [], k=7, prebuilt=pre7
)
assert found7, "planted 7-LUT chunk must contain feasible rows"
base7, total7, chunk70 = pre7
chunk7 = -(-chunk70 // n) * n
_, feas7f, r17f, r07f = sharded_feasible_stream(
    plan, *base7, c7, total7, k=7, chunk=chunk7, compact=False
)
feas7f, r17f, r07f = (np.asarray(x) for x in (feas7f, r17f, r07f))
feas7, r17, r07 = (np.asarray(x) for x in (feas7, r17, r07))
assert (feas7 == feas7f).all()
assert (r17[feas7f] == r17f[feas7f]).all()
assert (r07[feas7f] == r07f[feas7f]).all()
print("STREAMCHECK7 %d ok rows=%d" % (pid, int(feas7f.sum())), flush=True)

# Fourth leg: job-sharded sweep (the pod-scale config-5 mode) — each
# process searches its own slice of the 16-permutation sweep on a mesh of
# its LOCAL devices (no cross-process collectives).  The parent asserts
# the two slices are disjoint and cover all permutations.
from sboxgates_tpu.search.multibox import (  # noqa: E402
    permute_sweep_jobs,
    process_slice,
    search_boxes_one_output,
)

boxes = permute_sweep_jobs(sbox, n_in)
mine = process_slice(boxes)
ctx4 = SearchContext(
    Options(lut_graph=True, randomize=False, seed=9),
    mesh_plan=MeshPlan(make_mesh(jax.local_devices())),
)
assert not ctx4.mesh_plan.spans_processes
res4 = search_boxes_one_output(
    ctx4, mine, 0, save_dir=None, log=lambda s: None, batched=False
)
solved = sorted(name for name, sts in res4.items() if sts)
assert len(solved) == len(mine), (solved, [b.name for b in mine])
print("SWEEP %d %s" % (pid, ",".join(solved)), flush=True)
