"""Contract tests for the pivot-stream tuning levers: the env-var
semantics and backend-string forms the README advertises (and the bench
A/B relies on) stay pinned here.
"""

import numpy as np
import pytest

from sboxgates_tpu.ops import sweeps
from sboxgates_tpu.ops.pallas_pivot import parse_block


def test_pivot_pipeline_env_and_backend_default(monkeypatch):
    from sboxgates_tpu.search.lut import pivot_pipeline

    # Explicit env wins in both directions.
    monkeypatch.setenv("SBG_PIVOT_PIPELINE", "0")
    assert pivot_pipeline() is False
    monkeypatch.setenv("SBG_PIVOT_PIPELINE", "1")
    assert pivot_pipeline() is True
    # Unset: per-backend default — tests run on CPU (conftest pins it),
    # where the measured sign says pipeline ON.
    monkeypatch.delenv("SBG_PIVOT_PIPELINE", raising=False)
    assert pivot_pipeline() is True


def test_parse_block_contract():
    assert parse_block("64x128") == (64, 128)
    assert parse_block("128X256") == (128, 256)
    with pytest.raises(ValueError, match="SBG_PALLAS_BLOCK"):
        parse_block("banana")
    with pytest.raises(ValueError, match="powers"):
        parse_block("96x128")
    with pytest.raises(ValueError, match="powers"):
        parse_block("0x64")
    # The bench's backend-string form names the right lever in errors.
    with pytest.raises(ValueError, match="backend"):
        parse_block("65x128", source="backend")


def _stream_args_tiny():
    """Minimal well-formed arguments for backend-validation calls (the
    stream raises before tracing for bad static configs)."""
    z8 = np.zeros((4, 8, 8), np.uint32)
    return dict(
        tables=np.zeros((16, 8), np.uint32), lc1=z8, lc0=z8, hc=z8,
        lowvalid=np.zeros(8, bool), highvalid=np.zeros(8, bool),
        descs=np.zeros((1, 5), np.int32), start_t=0, t_end=0,
        w_tab=np.zeros((10, 8), np.int32),
        m_tab=np.zeros((10, 8), np.int32), seed=1,
    )


def test_stream_backend_validation():
    a = _stream_args_tiny()

    def call(**kw):
        sweeps.lut5_pivot_stream(
            a["tables"], a["lc1"], a["lc0"], a["hc"], a["lowvalid"],
            a["highvalid"], a["descs"], a["start_t"], a["t_end"],
            a["w_tab"], a["m_tab"], a["seed"], tl=8, th=8, **kw,
        )

    with pytest.raises(ValueError, match="unknown pivot backend"):
        call(backend="cuda")
    with pytest.raises(ValueError, match="tile_batch=1"):
        call(backend="pallas", tile_batch=2)
    with pytest.raises(ValueError, match="tile_batch=1"):
        call(backend="pallas_pre:128x128", tile_batch=2)
    with pytest.raises(ValueError, match="only applies to pallas"):
        call(backend="xla:64x128")
    with pytest.raises(ValueError, match="backend"):
        call(backend="pallas:65x128")
    with pytest.raises(ValueError, match="unknown pivot backend"):
        call(backend="pallasx:64x128")
