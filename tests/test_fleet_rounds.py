"""Fleet-merged round chains: the PR 11 fused round driver stacked on
the PR 8 fleet jobs axis.

Two dispatch forms are gated bit-identical to the per-lane
:func:`run_round_chain` reference: the explicit lockstep
``fleet_round_driver`` kernel (:func:`run_fleet_round_chains`) and the
rendezvous-merged path (concurrent lanes' ``round_driver`` windows
submitting through one :class:`FleetRendezvous` — the serve merged-wave
shape).  All tests run in-process on the planted-chain fixture
(tests/planted.py), so the file stays tier-1-cheap.
"""

import threading

import numpy as np
import pytest

from planted import build_round_chain
from sboxgates_tpu.search import Options, SearchContext
from sboxgates_tpu.search.fleet import FleetRendezvous, fleet_stats_into
from sboxgates_tpu.search.rounds import (
    run_fleet_round_chains,
    run_round_chain,
)
from sboxgates_tpu.search.serve import JobView


def _sig(st):
    return (
        st.tables.tobytes(),
        tuple((g.type, g.in1, g.in2, g.in3, g.function) for g in st.gates),
    )


def _ctx(seed=5):
    return SearchContext(Options(
        lut_graph=True, randomize=True, seed=seed, warmup=False,
        parallel_mux=False, native_engine=False,
    ))


def _lane_case(i):
    """Lane i's planted chain: distinct seeds, one lane (i == 2) ending
    in a target the round kernel cannot finish — the per-lane fallback
    path.  The fallback lane uses the SMALLEST planted state (a 7-leaf
    LUT tree over the bare 8 inputs): the host recursion it exists to
    trigger then sweeps a C(~12, 7) space in ~1 s instead of tens of
    seconds (the tier-1 budget discipline), while the other lanes share
    one shape class so their window compiles amortize across tests."""
    if i == 2:
        return build_round_chain(
            n_rounds=2, gates0=8, seed=22, deep_last=True,
        )
    return build_round_chain(n_rounds=6, gates0=12, seed=20 + i)


def _reference(n_lanes, rounds_per_dispatch=4):
    base = _ctx()
    refs = []
    for i in range(n_lanes):
        st, rounds = _lane_case(i)
        v = JobView(base, 1000 + i)
        outs = run_round_chain(
            v, st, rounds, rounds_per_dispatch=rounds_per_dispatch,
        )
        refs.append((tuple(outs), _sig(st), v.rng_snapshot()))
    return refs


def test_fleet_round_chains_bit_identical_with_fallback_lane():
    """The lockstep driver: 4 lanes (one with a host-fallback round)
    advance through fleet_round_driver dispatches — per-lane circuits,
    output ids, and PRNG positions byte-identical to run_round_chain on
    each lane alone, with the whole wave's windows collapsing to a
    handful of dispatches."""
    refs = _reference(4)
    base = _ctx()
    lanes = []
    for i in range(4):
        st, rounds = _lane_case(i)
        lanes.append((JobView(base, 1000 + i), st, rounds))
    outs = run_fleet_round_chains(base, lanes, rounds_per_dispatch=4)
    for i, (v, st, _rounds) in enumerate(lanes):
        assert (tuple(outs[i]), _sig(st), v.rng_snapshot()) == refs[i], (
            f"lane {i} diverged from its standalone chain"
        )
        # The fallback lane's counter landed on ITS view.
        if i == 2:
            assert v.stats["round_driver_fallbacks"] == 1
        else:
            assert v.stats["round_driver_fallbacks"] == 0
    # 4 lanes x 6-7 rounds at 4 rounds/dispatch: a couple of wave
    # windows, not lanes x windows.
    assert base.stats["device_dispatches"] <= 4


def test_fleet_round_chains_dispatch_ratio():
    """The combined-axis claim: L lanes x R rounds/dispatch means the
    per-round reference loop's L x rounds dispatches collapse to
    ceil(rounds / R) wave windows."""
    n_lanes, n_rounds, rpd = 4, 8, 8
    base = _ctx(seed=9)
    lanes = []
    for i in range(n_lanes):
        st, rounds = build_round_chain(
            n_rounds=n_rounds, gates0=12, seed=40 + i,
        )
        lanes.append((JobView(base, 2000 + i), st, rounds))
    outs = run_fleet_round_chains(base, lanes, rounds_per_dispatch=rpd)
    assert all(len(o) == n_rounds for o in outs)
    # All 4 lanes' 8 rounds in ONE dispatch: ratio
    # 1 / (lanes x rounds) vs the per-lane per-round loop.
    assert base.stats["device_dispatches"] == 1
    for v, _st, _r in lanes:
        assert v.stats["round_driver_fallbacks"] == 0


def test_rendezvous_merged_chain_windows_bit_identical():
    """The serve merged-wave shape: concurrent lanes running plain
    run_round_chain over ONE shared FleetRendezvous merge their
    round_driver windows into single jit(vmap) dispatches — per-lane
    results and PRNG streams identical to the direct windows."""
    refs = _reference(3)
    base = _ctx()
    rdv = FleetRendezvous(3, warmer=None)
    results = [None] * 3
    errors = []

    def worker(i):
        try:
            st, rounds = _lane_case(i)
            v = JobView(base, 1000 + i, rdv=rdv)
            outs = run_round_chain(v, st, rounds, rounds_per_dispatch=4)
            results[i] = (tuple(outs), _sig(st), v.rng_snapshot())
        except BaseException as e:  # surfaced after join
            errors.append(e)
        finally:
            rdv.finish()

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(3)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for i in range(3):
        assert results[i] == refs[i], f"lane {i} diverged when merged"
    fleet_stats_into(base, rdv)
    assert base.stats["fleet_submits"] >= 3
    # Merging happened: fewer dispatches than submitted windows.
    assert (
        base.stats["fleet_dispatches"] + base.stats["fleet_singletons"]
        < base.stats["fleet_submits"]
    )


def test_chain_warm_specs_match_live_dispatch(monkeypatch):
    """note_chain's AOT builds must key exactly like the live merged
    windows: after warming, a merged wave window is a fleet warm HIT
    (the (jobs_bucket, gate_bucket, chain-length) wave-shape specs)."""
    from sboxgates_tpu.search import warmup as W

    monkeypatch.setenv("SBG_WARMUP", "1")  # conftest defaults it off
    plan = W.WarmPlan.from_context(_ctx())
    st, _rounds = _lane_case(0)
    jobs = W.chain_warm_specs(plan, st.num_gates, 2, 4)
    # Both merged forms enumerated: the rendezvous-wrapped round_driver
    # and the pre-stacked fleet_round_driver.
    labels = sorted(j[4] for j in jobs)
    assert labels == ["fleet_round_driver", "round_driver"]
    # Compile the rendezvous form and serve a live merged window warm.
    warmer = W.KernelWarmer(plan, enabled=True)
    try:
        warmer.note_chain(st.num_gates, 2, 4)
        assert warmer.wait_idle(120)
        base = _ctx()
        rdv = FleetRendezvous(2, warmer=warmer)
        errors = []

        def worker(i):
            try:
                stl, rounds = build_round_chain(
                    n_rounds=4, gates0=st.num_gates, seed=60 + i,
                )
                v = JobView(base, 3000 + i, rdv=rdv)
                run_round_chain(v, stl, rounds, rounds_per_dispatch=4)
            except BaseException as e:
                errors.append(e)
            finally:
                rdv.finish()

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert rdv.stats["fleet_warm_hits"] >= 1, dict(rdv.stats)
    finally:
        warmer.shutdown()
        W.drop_warm_cache()


def test_chained_generate_graph_bit_identical_across_n():
    """Options.chain_rounds: the greedy chained-outputs driver produces
    byte-identical circuits for every rounds-per-dispatch value, and
    fewer dispatches at higher values."""
    from sboxgates_tpu.graph.state import State
    from sboxgates_tpu.search import generate_graph, make_targets
    from sboxgates_tpu.search.fleet import toy_fleet_boxes
    from sboxgates_tpu.utils.sbox import parse_sbox

    bj = toy_fleet_boxes(1)[0]
    text = " ".join("%02x" % v for v in bj.sbox[:8])
    sbox, ni = parse_sbox(text)
    sigs, disps = [], []
    for cr in (1, 8):
        ctx = SearchContext(Options(
            lut_graph=True, randomize=False, seed=11, warmup=False,
            host_small_steps=False, native_engine=False, chain_rounds=cr,
        ))
        res = generate_graph(
            ctx, State.init_inputs(ni), make_targets(sbox),
            save_dir=None, log=lambda s: None,
        )
        assert len(res) == 1
        sigs.append(_sig(res[0]))
        disps.append(int(ctx.stats["device_dispatches"]))
    assert sigs[0] == sigs[1]
    assert disps[1] <= disps[0]
