"""Native runtime (csrc/runtime.cpp) vs. the pure-Python/JAX paths."""

import itertools
import os

import numpy as np
import pytest

from sboxgates_tpu import native
from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import GATES, State
from sboxgates_tpu.graph import xmlio
from sboxgates_tpu.ops import combinatorics as comb
from sboxgates_tpu.utils.sbox import parse_sbox

SBOXES = os.path.join(os.path.dirname(__file__), "..", "sboxes")

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native lib unavailable: {native.build_error()}"
)


#: Compile-latency telemetry (search/warmup.py): legitimately differs
#: between a native-routed context (no device dispatch, no jit compile)
#: and its device twin — excluded from the sweep-verdict stat parity.
_TELEMETRY_KEYS = frozenset((
    "kernel_compiles", "compile_stall_s", "warm_hits", "warm_misses",
    "table_uploads", "table_cache_hits",
    # Execution-path counter, not a sweep-semantics one: the native
    # route's whole point is zero device dispatches.
    "device_dispatches",
))


def _sweep_stats(ctx) -> dict:
    return {k: v for k, v in ctx.stats.items() if k not in _TELEMETRY_KEYS}


def _state_bytes(st: State) -> bytes:
    """The serialized layout state_fingerprint absorbs (xmlio docstring)."""
    import struct

    parts = [
        struct.pack(
            "<iiHH8H4x",
            0,
            0,
            st.max_gates & 0xFFFF,
            st.num_gates & 0xFFFF,
            *[o & 0xFFFF for o in st.outputs],
        )
    ]
    for i, g in enumerate(st.gates):
        parts.append(st.tables[i].astype("<u4").tobytes())
        parts.append(
            struct.pack(
                "<iHHHB21x",
                g.type,
                g.in1 & 0xFFFF,
                g.in2 & 0xFFFF,
                g.in3 & 0xFFFF,
                g.function & 0xFF,
            )
        )
    return b"".join(parts)


def _rand_state(seed: int, num_inputs: int = 6, extra: int = 10) -> State:
    rng = np.random.default_rng(seed)
    st = State.init_inputs(num_inputs)
    while st.num_gates < num_inputs + extra:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        kind = rng.integers(0, 3)
        if kind == 0:
            st.add_gate(int(rng.choice([bf.AND, bf.OR, bf.XOR, bf.NAND])), int(a), int(b), GATES)
        elif kind == 1:
            st.add_not_gate(int(a), GATES)
        else:
            c = int(rng.choice([x for x in range(st.num_gates) if x not in (a, b)]))
            st.add_lut(int(rng.integers(1, 256)), int(a), int(b), c)
    st.outputs[0] = st.num_gates - 1
    return st


def test_fingerprint_matches_python():
    for seed in range(5):
        st = _rand_state(seed)
        assert native.fingerprint(_state_bytes(st)) == xmlio.state_fingerprint(st)


def test_n_choose_k():
    for n in (0, 1, 7, 30, 100):
        for k in (0, 1, 3, 5, 7):
            assert native.n_choose_k(n, k) == comb.n_choose_k(n, k)


def test_combinations_from_rank_full_space():
    ref = np.asarray(list(itertools.combinations(range(9), 4)), dtype=np.int32)
    got = native.combinations_from_rank(9, 4, 0, 1000)
    assert got.shape == ref.shape
    assert (got == ref).all()


def test_combinations_from_rank_mid_stream():
    ref = np.asarray(list(itertools.combinations(range(12), 5)), dtype=np.int32)
    got = native.combinations_from_rank(12, 5, 100, 57)
    assert (got == ref[100:157]).all()
    # tail clipping
    total = comb.n_choose_k(12, 5)
    got = native.combinations_from_rank(12, 5, total - 3, 10)
    assert got.shape[0] == 3
    assert (got == ref[-3:]).all()


def test_stream_uses_native_and_matches_python():
    stream = comb.CombinationStream(10, 3, start=17)
    rows = stream.next_chunk(25)
    ref = np.asarray(list(itertools.combinations(range(10), 3)), dtype=np.int32)
    assert (rows == ref[17:42]).all()


def test_execute_circuit_matches_state_tables():
    for seed in range(5):
        st = _rand_state(seed, num_inputs=5, extra=12)
        g = st.num_gates
        types = np.array([x.type for x in st.gates], dtype=np.int32)
        in1 = np.array([x.in1 if x.in1 != 0xFFFF else -1 for x in st.gates], dtype=np.int32)
        in2 = np.array([x.in2 if x.in2 != 0xFFFF else -1 for x in st.gates], dtype=np.int32)
        in3 = np.array([x.in3 if x.in3 != 0xFFFF else -1 for x in st.gates], dtype=np.int32)
        funcs = np.array([x.function for x in st.gates], dtype=np.uint8)
        n_in = st.num_inputs
        itab = native.tables32_to_64(st.tables[:n_in])
        out = native.execute_circuit(types, in1, in2, in3, funcs, itab)
        expect = native.tables32_to_64(st.live_tables())
        assert (out == expect).all(), f"seed {seed}"


def test_lut5_search_cpu_finds_planted_decomposition():
    st = State.init_inputs(8)
    rng = np.random.default_rng(7)
    while st.num_gates < 12:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    outer = tt.eval_lut(0x6B, st.table(2), st.table(5), st.table(7))
    target = tt.eval_lut(0x9C, outer, st.table(3), st.table(9))
    mask = tt.mask_table(8)

    stream = comb.CombinationStream(st.num_gates, 5)
    combos = stream.next_chunk(1 << 12)
    idx, res = native.lut5_search_cpu(
        native.tables32_to_64(st.live_tables()),
        native.tables32_to_64(target),
        native.tables32_to_64(mask),
        combos,
    )
    assert idx >= 0
    a, b, c, d, e = res["gates"]
    got = tt.eval_lut(
        res["func_inner"],
        tt.eval_lut(res["func_outer"], st.table(a), st.table(b), st.table(c)),
        st.table(d),
        st.table(e),
    )
    assert bool(tt.eq_mask(got, target, mask))


def test_lut5_search_cpu_mt_matches_serial():
    """The threaded CPU driver (the measured-socket baseline,
    sbg_lut5_search_cpu_mt) must return the global first hit in combo
    order — identical index and decomposition to the serial scan — for
    every thread count, including counts that don't divide the space."""
    st = State.init_inputs(8)
    rng = np.random.default_rng(7)
    while st.num_gates < 12:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    outer = tt.eval_lut(0x6B, st.table(2), st.table(5), st.table(7))
    target = tt.eval_lut(0x9C, outer, st.table(3), st.table(9))
    mask = tt.mask_table(8)
    combos = comb.CombinationStream(st.num_gates, 5).next_chunk(1 << 12)
    args = (
        native.tables32_to_64(st.live_tables()),
        native.tables32_to_64(target),
        native.tables32_to_64(mask),
        combos,
    )
    base = native.lut5_search_cpu(*args)
    assert base[0] >= 0
    for threads in (1, 2, 3, 8):
        assert native.lut5_search_cpu_mt(*args, threads) == base, threads


def test_lut5_search_cpu_no_false_positives():
    with open("sboxes/rijndael.txt") as f:
        sbox, n = parse_sbox(f.read())
    st = State.init_inputs(8)
    rng = np.random.default_rng(1)
    while st.num_gates < 11:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    # AES bit 0 is far beyond a single 5-LUT of XOR layers: must be a miss.
    idx, res = native.lut5_search_cpu(
        native.tables32_to_64(st.live_tables()),
        native.tables32_to_64(tt.target_table(sbox, 0)),
        native.tables32_to_64(tt.mask_table(n)),
        comb.CombinationStream(st.num_gates, 5).next_chunk(1 << 9),
    )
    assert idx == -1 and res is None


# -- fused gate-mode node step (sbg_gate_step) ----------------------------


def _step_contexts(seed, **opt_kwargs):
    """(native-routed, device-routed) contexts with identical options and
    PRNG streams."""
    from sboxgates_tpu.search import Options, SearchContext

    a = SearchContext(
        Options(seed=seed, host_small_steps=True, **opt_kwargs)
    )
    b = SearchContext(
        Options(seed=seed, host_small_steps=False, **opt_kwargs)
    )
    return a, b


def _rand_gate_state(rng, num_inputs, extra):
    st = State.init_inputs(num_inputs)
    while st.num_gates < num_inputs + extra:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        if rng.integers(0, 4) == 0:
            st.add_not_gate(int(a), GATES)
        else:
            st.add_gate(
                int(rng.choice([bf.AND, bf.OR, bf.XOR])), int(a), int(b), GATES
            )
    return st


@pytest.mark.parametrize("randomize", [False, True])
@pytest.mark.parametrize("try_nots", [False, True])
def test_gate_step_native_bitwise_matches_kernel(randomize, try_nots):
    """The native node step must return the kernel's exact verdict — same
    step, same selected candidate — in both selection modes, across states
    that exercise every step (existing-gate hits, complements, pairs,
    NOT-pairs, triples, and misses)."""
    rng = np.random.default_rng(99)
    steps_seen = set()
    for case in range(24):
        num_inputs = int(rng.integers(3, 7))
        extra = int(rng.integers(0, 9))
        st = _rand_gate_state(rng, num_inputs, extra)
        mask = tt.mask_table(num_inputs)
        kind = case % 4
        if kind == 0:  # random target: usually a triple hit or a miss
            target = np.asarray(
                rng.integers(0, 2**32, size=8, dtype=np.uint32)
            ) & np.asarray(mask)
        elif kind == 1:  # existing gate (or complement) hit
            gid = int(rng.integers(0, st.num_gates))
            target = st.table(gid) if rng.integers(0, 2) else ~st.table(gid)
            target = np.asarray(target) & np.asarray(mask)
        elif kind == 2:  # pair hit
            a, b = rng.choice(st.num_gates, size=2, replace=False)
            target = np.asarray(
                tt.eval_gate2(bf.NAND, st.table(int(a)), st.table(int(b)))
            ) & np.asarray(mask)
        else:  # partial mask (mux-recursion shape)
            sel = st.table(int(rng.integers(0, num_inputs)))
            mask = np.asarray(mask) & ~np.asarray(sel)
            target = np.asarray(
                rng.integers(0, 2**32, size=8, dtype=np.uint32)
            ) & mask
        seed = int(rng.integers(0, 2**31)) if randomize else None
        ctx_n, ctx_d = _step_contexts(
            seed, randomize=randomize, try_nots=try_nots
        )
        got_n = ctx_n.gate_step(st, target, mask)
        got_d = ctx_d.gate_step(st, target, mask)
        if got_d[0] == 0:
            # miss: the kernel's payload fields are unspecified junk
            # (last chunk's argmax row); only the step must agree
            assert got_n[0] == 0, f"case {case}: native {got_n}, kernel miss"
        else:
            assert got_n == got_d, (
                f"case {case}: native {got_n} != kernel {got_d}"
            )
        assert _sweep_stats(ctx_n) == _sweep_stats(ctx_d), f"case {case}"
        steps_seen.add(got_n[0])
    assert {1, 2, 3}.issubset(steps_seen), steps_seen


def test_gate_step_native_full_search_identical():
    """End-to-end: a non-randomized gate-mode search must produce the
    identical circuit whichever path executes the node sweeps."""
    from sboxgates_tpu.core.ttable import mask_table
    from sboxgates_tpu.search import make_targets
    from sboxgates_tpu.search.kwan import create_circuit

    with open("sboxes/crypto1_fa.txt") as f:
        sbox, n = parse_sbox(f.read())
    targets = make_targets(sbox)
    circuits = []
    for host in (True, False):
        from sboxgates_tpu.search import Options, SearchContext

        ctx = SearchContext(
            Options(seed=5, randomize=False, host_small_steps=host,
                    parallel_mux=False)
        )
        st = State.init_inputs(n)
        out = create_circuit(ctx, st, targets[0], mask_table(n), [])
        assert out != 0xFFFF
        circuits.append(
            [(g.type, g.in1, g.in2, g.in3, g.function) for g in st.gates]
        )
    assert circuits[0] == circuits[1]


def test_gate_step_native_matches_kernel_large_bucket():
    """g > 64 routes through the 512-row bucket grid: the native pair
    index and triple rank must still decode identically to the kernel."""
    rng = np.random.default_rng(3)
    st = _rand_gate_state(rng, 8, 72)  # g = 80 -> bucket 512
    mask = tt.mask_table(8)
    a, b = rng.choice(st.num_gates, size=2, replace=False)
    planted = np.asarray(
        tt.eval_gate2(bf.NAND, st.table(int(a)), st.table(int(b)))
    ) & np.asarray(mask)
    rand = np.asarray(
        rng.integers(0, 2**32, size=8, dtype=np.uint32)
    ) & np.asarray(mask)
    for target in (planted, rand):
        for seed in (None, 77):
            ctx_n, ctx_d = _step_contexts(
                seed, randomize=seed is not None, try_nots=True
            )
            got_n = ctx_n.gate_step(st, target, mask)
            got_d = ctx_d.gate_step(st, target, mask)
            if got_d[0] == 0:
                assert got_n[0] == 0
            else:
                assert got_n == got_d
            assert _sweep_stats(ctx_n) == _sweep_stats(ctx_d)


@pytest.mark.parametrize("randomize", [False, True])
def test_lut_step_native_bitwise_matches_kernel(randomize):
    """The native LUT-mode head must return the kernel's exact verdict —
    same step, same payload — across states exercising scan hits, pair
    hits, 3-LUT hits, 5-LUT hits, exclusions, and misses."""
    rng = np.random.default_rng(7)
    steps_seen = set()
    for case in range(20):
        num_inputs = int(rng.integers(4, 8))
        extra = int(rng.integers(0, 7))
        st = _rand_gate_state(rng, num_inputs, extra)
        mask = tt.mask_table(num_inputs)
        inbits = []
        kind = case % 4
        if kind == 0:  # random target: 3-LUT hit or miss
            target = np.asarray(
                rng.integers(0, 2**32, size=8, dtype=np.uint32)
            ) & np.asarray(mask)
        elif kind == 1:  # planted 5-LUT decomposition
            gids = rng.choice(st.num_gates, size=5, replace=False)
            outer = tt.eval_lut(
                int(rng.integers(1, 255)),
                st.table(int(gids[0])), st.table(int(gids[1])),
                st.table(int(gids[2])),
            )
            target = np.asarray(
                tt.eval_lut(
                    int(rng.integers(1, 255)), outer,
                    st.table(int(gids[3])), st.table(int(gids[4])),
                )
            ) & np.asarray(mask)
        elif kind == 2:  # scan/complement hit
            gid = int(rng.integers(0, st.num_gates))
            target = st.table(gid) if rng.integers(0, 2) else ~st.table(gid)
            target = np.asarray(target) & np.asarray(mask)
        else:  # partial mask + exclusions (mux recursion shape)
            bit = int(rng.integers(0, num_inputs))
            inbits = [bit]
            sel = st.table(bit)
            mask = np.asarray(mask) & ~np.asarray(sel)
            target = np.asarray(
                rng.integers(0, 2**32, size=8, dtype=np.uint32)
            ) & mask
        seed = int(rng.integers(0, 2**31)) if randomize else None
        ctx_n, ctx_d = _step_contexts(
            seed, randomize=randomize, lut_graph=True
        )
        got_n = tuple(int(x) for x in ctx_n.lut_step(st, target, mask, inbits))
        got_d = tuple(int(x) for x in ctx_d.lut_step(st, target, mask, inbits))
        if got_d[0] == 0:
            assert got_n[0] == 0, f"case {case}: native {got_n}, kernel miss"
            # examined counters must still agree on a miss
            assert got_n[6:] == got_d[6:], f"case {case}"
        else:
            assert got_n == got_d, (
                f"case {case}: native {got_n} != kernel {got_d}"
            )
        assert _sweep_stats(ctx_n) == _sweep_stats(ctx_d), f"case {case}"
        steps_seen.add(got_n[0])
    assert {1, 4, 5}.issubset(steps_seen), steps_seen


def test_lut_step_native_full_search_identical():
    """End-to-end: a LUT-mode search must produce the identical circuit
    whichever path executes the head sweeps (fixed seed, both modes).

    Problem size: a 5-input random bijective S-box (PR 13 shrink — was
    four full DES S1 searches at ~40 s, promoted to ``slow`` in PR 12).
    The 5-input walk is a real multi-node mux recursion whose device
    arm makes ~50 dispatches across the pair / 3-LUT / 5-LUT / staged
    7-LUT heads, so the routing-equality claim keeps its end-to-end
    teeth at ~1/4 the wall clock; the per-verdict parity of every head
    at DES-and-larger sizes stays pinned by
    test_lut_step_native_bitwise_matches_kernel."""
    from sboxgates_tpu.core.ttable import mask_table
    from sboxgates_tpu.graph.xmlio import state_fingerprint
    from sboxgates_tpu.search import make_targets
    from sboxgates_tpu.search.kwan import create_circuit

    rng = np.random.default_rng(9)
    sbox = np.zeros(256, dtype=np.uint8)
    sbox[:32] = rng.permutation(32)
    n = 5
    targets = make_targets(sbox)
    for randomize in (False, True):
        prints = []
        for host in (True, False):
            from sboxgates_tpu.search import Options, SearchContext

            # native_engine off: this test compares the per-node step
            # routing (host vs device), not the engines — a randomized
            # engine run draws from its own PRNG stream by design.
            ctx = SearchContext(
                Options(seed=11, randomize=randomize, lut_graph=True,
                        host_small_steps=host, parallel_mux=False,
                        native_engine=False)
            )
            st = State.init_inputs(n)
            out = create_circuit(ctx, st, targets[0], mask_table(n), [])
            assert out != 0xFFFF
            st.outputs[0] = out
            prints.append(state_fingerprint(st))
            if not host:
                # The shrunk problem must still drive the device path:
                # a search that never dispatched proves nothing about
                # routing equality.
                assert ctx.stats["device_dispatches"] > 0
                assert ctx.stats["lut5_candidates"] > 0
        assert prints[0] == prints[1], f"randomize={randomize}"


def test_gate_step_native_not_pair_and_triple_verdicts():
    """Forces the step-4 (NOT-pair) and step-5 (triple stream) verdicts —
    the two most intricate native/kernel correspondences — instead of
    leaving their coverage to RNG luck."""
    rng = np.random.default_rng(17)
    st = State.init_inputs(6)
    while st.num_gates < 14:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    mask = np.asarray(tt.mask_table(6))

    # NAND of two gates: not in the AND/OR/XOR pair table, present in the
    # NOT-augmented table -> step 4.
    nand = np.asarray(tt.eval_gate2(bf.NAND, st.table(6), st.table(9))) & mask
    # (a & b) ^ c: a 2-level composition in avail_3 without polarities ->
    # step 5 via the chunked triple stream.
    tri = np.asarray(
        tt.eval_gate2(
            bf.XOR,
            tt.eval_gate2(bf.AND, st.table(7), st.table(10)),
            st.table(12),
        )
    ) & mask
    for target, want_step, try_nots in (
        (nand, 4, True),
        (tri, 5, True),
        (tri, 5, False),
    ):
        for seed in (None, 1234):
            ctx_n, ctx_d = _step_contexts(
                seed, randomize=seed is not None, try_nots=try_nots
            )
            got_n = ctx_n.gate_step(st, target, mask)
            got_d = ctx_d.gate_step(st, target, mask)
            assert got_d[0] == want_step, (got_d, want_step, try_nots, seed)
            assert got_n == got_d, (got_n, got_d)
            assert _sweep_stats(ctx_n) == _sweep_stats(ctx_d)


def test_lut_step_native_overflow_parity():
    """5-LUT solver overflow (status 6): with solve_rows=1 and a target
    admitting several feasible but undecomposable 5-tuples (majority-5
    needs 4 outer classes, a single outer bit gives 2), native and kernel
    must agree on the overflow verdict and resume point."""
    import itertools

    import jax.numpy as jnp

    from sboxgates_tpu import native
    from sboxgates_tpu.ops import sweeps
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.context import pick_chunk, STREAM_CHUNK

    st = State.init_inputs(8)
    st.add_not_gate(0, GATES)        # gate 8 = ~in0
    st.add_not_gate(8, GATES)        # gate 9: table == in0 (duplicate)
    g = st.num_gates
    mask = np.asarray(tt.mask_table(8))
    ins = [np.asarray(st.table(i)) for i in range(5)]
    maj = np.zeros(8, dtype=np.uint32)
    for trip in itertools.combinations(range(5), 3):
        maj |= ins[trip[0]] & ins[trip[1]] & ins[trip[2]]
    target = maj & mask

    total3 = comb.n_choose_k(g, 3)
    total5 = comb.n_choose_k(g, 5)
    chunk3 = pick_chunk(total3, STREAM_CHUNK[3])
    chunk5 = pick_chunk(total5, STREAM_CHUNK[5])
    splits, w_tab, m_tab = sweeps.lut5_split_tables()

    ctx = SearchContext(Options(seed=1, lut_graph=True))
    tables = ctx.device_tables(st)
    b = tables.shape[0]
    combos = ctx._pair_combos(b)
    excl = ctx.excl_array([])
    for seed in (-1, 555):
        got_d = np.asarray(
            sweeps.lut_step_stream(
                tables,
                jnp.arange(b) < g,
                combos,
                (np.asarray(ctx._pair_combos_np(b)) < g).all(axis=1),
                ctx.binom,
                g,
                jnp.asarray(target),
                jnp.asarray(np.asarray(mask)),
                jnp.asarray(excl),
                total3,
                total5,
                ctx.pair_table,
                jnp.asarray(w_tab),
                jnp.asarray(m_tab),
                seed,
                chunk3=chunk3,
                chunk5=chunk5,
                has5=True,
                solve_rows=1,
            )
        )
        got_n = native.lut_step(
            native.tables32_to_64(st.live_tables()),
            g,
            b,
            native.tables32_to_64(target),
            native.tables32_to_64(mask),
            ctx.pair_table_np,
            excl,
            total3,
            chunk3,
            True,
            total5,
            chunk5,
            1,
            w_tab,
            m_tab,
            seed,
        )
        assert got_d[0] == 6, got_d  # overflow actually exercised
        assert got_n[0] == 6
        # resume point and examined counters must agree exactly
        assert int(got_n[1]) == int(got_d[1])
        assert tuple(got_n[6:]) == tuple(got_d[6:])


@pytest.mark.parametrize("randomize", [False, True])
def test_lut7_step_native_matches_kernel(randomize):
    """The hybrid 7-LUT step (native stage A + device solve on hits only)
    must craft the kernel's exact verdict: same status, same selected
    tuple/decomposition on hits, same examined/solved counters always."""
    from sboxgates_tpu.search.context import lut_head_has7

    rng = np.random.default_rng(23)
    statuses = set()
    for case in range(12):
        num_inputs = int(rng.integers(4, 8))
        extra = int(rng.integers(3, 8))
        st = _rand_gate_state(rng, num_inputs, extra)
        if not lut_head_has7(st.num_gates):
            continue
        mask = np.asarray(tt.mask_table(num_inputs))
        inbits = [int(rng.integers(0, num_inputs))] if case % 3 == 2 else []
        if case % 2 == 0:  # plant a 7-LUT decomposition
            gids = rng.choice(st.num_gates, size=7, replace=False)
            t = [st.table(int(x)) for x in gids]
            outer = tt.eval_lut(int(rng.integers(1, 255)), t[0], t[1], t[2])
            middle = tt.eval_lut(int(rng.integers(1, 255)), t[3], t[4], outer)
            target = np.asarray(
                tt.eval_lut(int(rng.integers(1, 255)), middle, t[5], t[6])
            ) & mask
        else:
            target = np.asarray(
                rng.integers(0, 2**32, size=8, dtype=np.uint32)
            ) & mask
        seed = int(rng.integers(0, 2**31)) if randomize else None
        ctx_n, ctx_d = _step_contexts(
            seed, randomize=randomize, lut_graph=True
        )
        got_n = tuple(int(x) for x in ctx_n.lut7_step(st, target, mask, inbits))
        got_d = tuple(int(x) for x in ctx_d.lut7_step(st, target, mask, inbits))
        # full verdict parity — on misses too (the top feasible row's
        # rank/constraints and sigma=-1 are reproduced exactly)
        assert got_n == got_d, f"case {case}: {got_n} vs {got_d}"
        assert _sweep_stats(ctx_n) == _sweep_stats(ctx_d), f"case {case}"
        statuses.add(got_d[0])
    assert {0, 1}.issubset(statuses), statuses


@pytest.mark.parametrize("seed", [-1, 991])
def test_lut7_solve_small_matches_device_solver(seed):
    """Direct stage-B parity: the host pair-matrix solver must reproduce
    sweeps.lut7_solve's exact verdict (found/best_t/sigma/flat) on the
    same rows — including constraint rows derived from real tuples."""
    import jax.numpy as jnp

    from sboxgates_tpu.ops import sweeps

    rng = np.random.default_rng(5)
    idx_tab, pp_tab = sweeps.lut7_pair_tables()
    solve7 = 256
    hits = 0
    for case in range(6):
        take = int(rng.integers(1, 9))
        if case < 3:
            # random sparse constraints: usually decomposable
            density = 0.01 + 0.02 * case
            r1 = (rng.random((take, 128)) < density)
            r0 = (rng.random((take, 128)) < density) & ~r1
            # packing: bit c of word w = cell w*32+c
            def pack2(b):
                out = np.zeros((take, 4), np.uint32)
                for t in range(take):
                    for c in range(128):
                        if b[t, c]:
                            out[t, c // 32] |= np.uint32(1 << (c % 32))
                return out
            sr1, sr0 = pack2(r1), pack2(r0)
        else:
            # all-conflict rows (never decomposable) mixed with one
            # moderately-constrained row
            sr1 = np.full((take, 4), 0xFFFFFFFF, np.uint32)
            sr0 = np.full((take, 4), 0xFFFFFFFF, np.uint32)
            sr1[0] = rng.integers(0, 2**32, 4, dtype=np.uint32)
            sr0[0] = ~sr1[0]
        pad1 = np.full((solve7, 4), 0xFFFFFFFF, np.uint32); pad1[:take] = sr1
        pad0 = np.full((solve7, 4), 0xFFFFFFFF, np.uint32); pad0[:take] = sr0
        dev = np.asarray(sweeps.lut7_solve(
            jnp.asarray(pad1), jnp.asarray(pad0),
            jnp.asarray(idx_tab), jnp.asarray(pp_tab), seed,
        ))
        nat = native.lut7_solve_small(sr1, sr0, solve7, idx_tab, seed)
        # full verdict parity including the miss encoding (sigma = -1)
        assert tuple(int(x) for x in nat) == tuple(int(x) for x in dev), (
            case, nat, dev,
        )
        hits += int(dev[0])
    assert hits >= 2


def test_gate_engine_matches_python_engine():
    """The native gate-mode ENGINE (csrc sbg_gate_engine) must produce
    the bit-identical circuit to the Python recursion when not
    randomizing — same gates, same order, same SAT metric — across
    plain, SAT+NOT, and restricted-gate-set configs."""
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import State
    from sboxgates_tpu.search import Options, SearchContext, make_targets
    from sboxgates_tpu.search.kwan import create_circuit
    from sboxgates_tpu.utils.sbox import load_sbox

    cases = [
        ("crypto1_fa", 0, {}),
        ("des_s1", 0, {}),
        ("des_s1", 1, {"metric": 1, "try_nots": True}),
        ("des_s1", 2, {"avail_gates_bitfield": 10694, "try_nots": True}),
    ]
    for box, bit, kw in cases:
        sbox, n = load_sbox(os.path.join(SBOXES, f"{box}.txt"))
        targets = make_targets(sbox)
        mask = tt.mask_table(n)
        res = {}
        for engine in (True, False):
            ctx = SearchContext(
                Options(seed=1, randomize=False, native_engine=engine, **kw)
            )
            st = State.init_inputs(n)
            out = create_circuit(ctx, st, targets[bit], mask, [])
            res[engine] = (
                out,
                [(g.type, g.in1, g.in2) for g in st.gates],
                st.sat_metric,
            )
            if out != 0xFFFF:
                st.verify_gate(out, targets[bit], mask)
        assert res[True] == res[False], (box, bit, kw)


def test_gate_engine_randomized_valid_and_deterministic():
    """Randomized engine runs: deterministic per seed, valid circuits,
    and different seeds explore different circuits."""
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import State
    from sboxgates_tpu.search import Options, SearchContext, make_targets
    from sboxgates_tpu.search.kwan import create_circuit
    from sboxgates_tpu.utils.sbox import load_sbox

    sbox, n = load_sbox(os.path.join(SBOXES, "des_s1.txt"))
    targets = make_targets(sbox)
    mask = tt.mask_table(n)

    def run(seed):
        ctx = SearchContext(Options(seed=seed))
        st = State.init_inputs(n)
        out = create_circuit(ctx, st, targets[0], mask, [])
        assert out != 0xFFFF
        st.verify_gate(out, targets[0], mask)
        return [(g.type, g.in1, g.in2) for g in st.gates]

    a1, a2, b = run(7), run(7), run(8)
    assert a1 == a2, "same seed must reproduce the same circuit"
    # The engine is deterministic per seed, so this comparison is stable:
    # seeds 7 and 8 are known to explore different circuits here, and a
    # broken rng_seed plumbing (constant stream) would make them equal.
    assert a1 != b, "different seeds must explore different circuits"


def test_lut_engine_matches_python_engine():
    """The native LUT-mode ENGINE (csrc sbg_lut_engine) must produce the
    bit-identical circuit to the Python recursion when not randomizing —
    same gates (including LUT functions), same order — across boxes that
    exercise 3-LUT, 5-LUT, 7-LUT, and mux nodes."""
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import State
    from sboxgates_tpu.search import Options, SearchContext, make_targets
    from sboxgates_tpu.search.kwan import create_circuit
    from sboxgates_tpu.utils.sbox import load_sbox

    for box, bit, kw in [
        ("crypto1_fa", 0, {}),
        ("crypto1_fc", 0, {}),
        ("des_s1", 0, {}),
        ("des_s1", 3, {}),
        ("des_s1", 1, {"avail_gates_bitfield": 10694}),
    ]:
        sbox, n = load_sbox(os.path.join(SBOXES, f"{box}.txt"))
        targets = make_targets(sbox)
        mask = tt.mask_table(n)
        res = {}
        for engine in (True, False):
            ctx = SearchContext(
                Options(
                    seed=1, randomize=False, lut_graph=True,
                    native_engine=engine, **kw,
                )
            )
            st = State.init_inputs(n)
            out = create_circuit(ctx, st, targets[bit], mask, [])
            res[engine] = (
                out,
                [
                    (g.type, g.in1, g.in2, g.in3, g.function)
                    for g in st.gates
                ],
            )
            if out != 0xFFFF:
                st.verify_gate(out, targets[bit], mask)
        assert res[True] == res[False], (box, bit, kw)


def _run_lut_engine_case(build, engine: bool, **kw):
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.kwan import create_circuit

    st, target, mask = build()
    ctx = SearchContext(
        Options(
            seed=2, lut_graph=True, randomize=False, native_engine=engine,
            **kw,
        )
    )
    out = create_circuit(ctx, st, target, mask, [])
    assert out != 0xFFFF
    st.verify_gate(out, target, mask)
    gates = [(g.type, g.in1, g.in2, g.in3, g.function) for g in st.gates]
    return out, gates, ctx


def test_lut_engine_continuation_services_pivot_states():
    """A pivot-sized state keeps the engine active: the device-work
    continuation services the pivot 5-LUT sweep and the native recursion
    resumes — bit-identical result to the Python engine, zero
    Python-driven nodes, no discarded exploration (round-3 bailed the
    whole call here and reran everything in Python)."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from planted import build_planted_lut5

    out_e, gates_e, ctx_e = _run_lut_engine_case(build_planted_lut5, True)
    out_p, gates_p, ctx_p = _run_lut_engine_case(build_planted_lut5, False)
    assert (out_e, gates_e) == (out_p, gates_p)
    # The service ran the pivot sweep (counting its candidate space) and
    # the engine, not the Python recursion, drove every node.
    assert ctx_e.stats["engine_devcalls"] >= 1
    assert ctx_e.stats["lut5_candidates"] == ctx_p.stats["lut5_candidates"] > 0
    assert ctx_e.stats.get("python_nodes", 0) == 0
    assert ctx_e.stats["engine_nodes"] >= 1


def test_lut_engine_continuation_services_staged_lut7():
    """A state whose 7-LUT space exceeds the single-chunk limit routes
    the staged search through the continuation service; the engine
    materializes the serviced decomposition bit-identically to the
    Python engine's.

    Problem size: the 22-gate planted state (PR 13 shrink — C(22,7) =
    171k still crosses the 2^17 single-chunk limit, so the staged
    routing and the stage-B device solve are exercised identically at
    half the stage-A work; the walk was the 24-gate shape at ~50 s,
    promoted to ``slow`` in PR 12)."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from planted import build_planted_lut7

    out_e, gates_e, ctx_e = _run_lut_engine_case(
        lambda: build_planted_lut7(22), True)
    out_p, gates_p, ctx_p = _run_lut_engine_case(
        lambda: build_planted_lut7(22), False)
    assert (out_e, gates_e) == (out_p, gates_p)
    assert ctx_e.stats["engine_devcalls"] >= 1
    assert ctx_e.stats["lut7_candidates"] == ctx_p.stats["lut7_candidates"] > 0
    assert ctx_e.stats["lut7_solved"] == ctx_p.stats["lut7_solved"] > 0
    assert ctx_e.stats.get("python_nodes", 0) == 0


def test_lut_engine_service_binds_per_context_views():
    """A RestartContext view inherits the base context's __dict__ —
    including any cached engine device-work service.  A devcall from the
    view's engine (host-only node whose 7-LUT phase is staged) must be
    serviced against the VIEW (its stats, its rng), not the base the
    cached closure was built for: the view counts the serviced work and
    the base's counters stay untouched until an explicit merge.

    Problem size: both walks use the 22-gate staged-lut7 planted state
    (PR 13 shrink — the priming walk needs any real engine devcall to
    cache a service closure, and the kind-3 staged service does that at
    a third of the old planted-lut5 pivot walk's cost; the pivot kind-1
    service keeps its own tier-1 coverage in
    test_lut_engine_continuation_services_pivot_states)."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from planted import build_planted_lut7

    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.batched import Rendezvous, RestartContext
    from sboxgates_tpu.search.kwan import create_circuit

    base = SearchContext(Options(seed=2, lut_graph=True, randomize=False))
    # Prime the base's service cache with a real engine+devcall run.
    st0, t0, m0 = build_planted_lut7(22)
    assert create_circuit(base, st0, t0, m0, []) != 0xFFFF
    assert base._lut_engine_service_fn[0] is base
    base_counts = dict(base.stats)

    view = RestartContext(base, 123, Rendezvous(1))
    # The inherited cache entry still names the base as its owner...
    assert view._lut_engine_service_fn[0] is base
    st, target, mask = build_planted_lut7(22)  # host-only, staged 7-LUT
    out = create_circuit(view, st, target, mask, [])
    assert out != 0xFFFF
    st.verify_gate(out, target, mask)
    # ...so the view must have built (and cached) its own.
    assert view._lut_engine_service_fn[0] is view
    assert base._lut_engine_service_fn[0] is base
    assert view.stats["engine_devcalls"] >= 1
    assert view.stats["lut7_candidates"] > 0
    # The serviced work was counted on the view, not leaked to the base.
    assert dict(base.stats) == base_counts


def test_engine_threaded_mux_service_machinery_parity(monkeypatch):
    """Fast tier-1 twin of the full threaded-mux parity test below:
    every device-work request is stubbed to a not-found verdict
    (identically in every arm), so the whole mux tree walks at native
    speed while the engine's THREADED fan-out still runs — concurrent
    branch threads, ctypes callbacks from each, bit-order fold under
    budget raises.  Results and summed counters must be bit-identical
    across serial, 8-thread, and wave-capped arms.  (The threaded
    PYTHON service's per-view plumbing keeps its own tier-1 coverage in
    test_lut_engine_service_binds_per_context_views; the un-stubbed
    whole-sweep walk is the slow twin below.)"""
    import sys
    from functools import reduce

    sys.path.insert(0, os.path.dirname(__file__))
    from planted import build_planted_lut5

    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.kwan import create_circuit

    def run(threads):
        monkeypatch.setenv("SBG_ENGINE_MUX_THREADS", str(threads))
        st, _, mask = build_planted_lut5()
        miss = reduce(
            lambda a, b: np.asarray(a) & np.asarray(b),
            [st.table(i) for i in range(8)],
        )
        st.max_gates = st.num_gates + 3
        ctx = SearchContext(Options(seed=2, lut_graph=True, randomize=False))

        def wrapped(kind, *args):
            return None

        ctx._lut_engine_service_fn = (ctx, wrapped)
        out = create_circuit(ctx, st, miss, mask, [])
        keys = ("engine_nodes", "engine_devcalls", "pair_candidates")
        return out, st.num_gates, {k: ctx.stats.get(k, 0) for k in keys}

    s_out, s_g, s_stats = run(1)
    t_out, t_g, t_stats = run(8)
    w_out, w_g, w_stats = run(2)
    assert (s_out, s_g, s_stats) == (t_out, t_g, t_stats)
    assert (s_out, s_g, s_stats) == (w_out, w_g, w_stats)
    # The branches really issued service requests: the root plus each
    # first-level branch asks for its 5-LUT sweep.
    assert s_stats["engine_devcalls"] >= 9


@pytest.mark.slow
def test_engine_threaded_mux_matches_serial(monkeypatch):
    """SBG_ENGINE_MUX_THREADS > 1 fans the outermost mux over C++
    threads whose branches service their device work concurrently
    (per-call context views).  Non-randomized results and the summed
    candidate counters must be bit-identical to the serial engine's —
    the fold stays in bit order.  The target (AND of all 8 inputs) is
    unrealizable from the XOR state, so both arms walk the whole mux
    tree; kind-3 requests are suppressed (the staged 7-LUT's C(50,7)
    stage A is minutes on CPU and identical in both arms).

    Marked slow: three full-tree walks with real C(50,5) pivot sweeps
    per node are ~4.5 min on a 2-core CPU host — the un-stubbed
    extension of the tier-1 machinery-parity twin above."""
    import sys
    from functools import reduce

    sys.path.insert(0, os.path.dirname(__file__))
    from planted import build_planted_lut5

    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.kwan import _lut_engine_service, create_circuit

    def run(threads):
        monkeypatch.setenv("SBG_ENGINE_MUX_THREADS", str(threads))
        st, _, mask = build_planted_lut5()
        miss = reduce(
            lambda a, b: np.asarray(a) & np.asarray(b),
            [st.table(i) for i in range(8)],
        )
        st.max_gates = st.num_gates + 3
        ctx = SearchContext(Options(seed=2, lut_graph=True, randomize=False))
        real = _lut_engine_service(ctx, threaded=threads > 1)

        def wrapped(kind, *args):
            return None if kind == 3 else real(kind, *args)

        ctx._lut_engine_service_fn = (ctx, wrapped)
        out = create_circuit(ctx, st, miss, mask, [])
        keys = (
            "engine_nodes", "engine_devcalls", "lut3_candidates",
            "lut5_candidates", "pair_candidates",
        )
        return out, st.num_gates, {k: ctx.stats.get(k, 0) for k in keys}

    s_out, s_g, s_stats = run(1)
    t_out, t_g, t_stats = run(8)
    assert (s_out, s_g) == (t_out, t_g)
    assert s_stats == t_stats, (s_stats, t_stats)
    # The lever is a concurrency CAP (wave launches): 2 must give the
    # identical result via 4 waves of 2 branches.
    w_out, w_g, w_stats = run(2)
    assert (s_out, s_g, s_stats) == (w_out, w_g, w_stats)
    # The mux branches really serviced device work: the root plus each
    # first-level branch runs a pivot 5-LUT sweep.
    assert s_stats["engine_devcalls"] >= 9


def test_lut_engine_service_kind2_overflow_resume():
    """The kind-2 device-work service (fused-head in-kernel solver
    overflow) must re-drive the flagged chunk and resume the stream —
    exercised directly against the service contract, since planting a
    genuine >1024-feasible-row overflow is not deterministic: from
    cstart=0 on a small planted state it must find the planted
    decomposition, and from past the end of the space it must miss."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from planted import build_planted_lut5_small

    from sboxgates_tpu.ops import combinatorics as comb_ops
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.kwan import _lut_engine_service
    from sboxgates_tpu.utils import sbox as _  # noqa: F401

    st, target, mask = build_planted_lut5_small()
    ctx = SearchContext(Options(seed=2, lut_graph=True, randomize=False))
    service = _lut_engine_service(ctx)
    tables = np.ascontiguousarray(st.live_tables())
    hit = service(
        2, tables, st.num_gates, np.asarray(target), np.asarray(mask),
        [], 0, 0, 0,
    )
    assert hit is not None and len(hit) == 7
    fo, fi, a, b, c, d, e = (int(x) for x in hit)
    got = tt.eval_lut(
        fi, tt.eval_lut(fo, st.table(a), st.table(b), st.table(c)),
        st.table(d), st.table(e),
    )
    assert bool(tt.eq_mask(got, target, mask))
    # Resuming past the end of the space must scan nothing and miss.
    total = comb_ops.n_choose_k(st.num_gates, 5)
    miss = service(
        2, tables, st.num_gates, np.asarray(target), np.asarray(mask),
        [], total, 0, 0,
    )
    assert miss is None


def test_lut_engine_bails_to_python_on_service_failure():
    """A broken device-work service degrades to the round-3 design: the
    engine bails and the Python engine finds (and verifies) the planted
    decomposition — robustness, not correctness, depends on the
    service."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from planted import build_planted_lut5

    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.kwan import create_circuit

    st, target, mask = build_planted_lut5()
    ctx = SearchContext(Options(seed=2, lut_graph=True, randomize=False))

    def broken_service(*args):
        raise RuntimeError("simulated device failure")

    # The cache entry is (owning_ctx, service) — kwan validates ownership.
    ctx._lut_engine_service_fn = (ctx, broken_service)
    out = create_circuit(ctx, st, target, mask, [])
    assert out != 0xFFFF
    st.verify_gate(out, target, mask)
    assert ctx.stats["lut5_candidates"] > 0
    assert ctx.stats.get("python_nodes", 0) >= 1


def test_lut_engine_randomized_valid_and_deterministic():
    """Randomized LUT-engine runs: deterministic per seed and the found
    circuits verify."""
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import State
    from sboxgates_tpu.search import Options, SearchContext, make_targets
    from sboxgates_tpu.search.kwan import create_circuit
    from sboxgates_tpu.utils.sbox import load_sbox

    sbox, n = load_sbox(os.path.join(SBOXES, "des_s1.txt"))
    targets = make_targets(sbox)
    mask = tt.mask_table(n)

    def run(seed):
        ctx = SearchContext(Options(seed=seed, lut_graph=True))
        st = State.init_inputs(n)
        out = create_circuit(ctx, st, targets[1], mask, [])
        assert out != 0xFFFF
        st.verify_gate(out, targets[1], mask)
        return [(g.type, g.in1, g.in2, g.in3, g.function) for g in st.gates]

    a1, a2, b = run(5), run(5), run(6)
    assert a1 == a2
    assert a1 != b
