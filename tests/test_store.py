"""Content-addressed result store tests: canonicalization property
tests (random tables under random input permutations/negations and
output complement map to ONE key, and stored circuits rewrite back to
the query frame verified over all 2^8 inputs), corruption and fault
tolerance (torn/digest-corrupt entries and injected ``store.*`` faults
degrade to miss-and-search, never a crash), and the serve integration
acceptance gates: a repeated query is served with ZERO device
dispatches bit-identically to a fresh search, and a drained search's
stored frontier resumes bit-identically across processes."""

import hashlib
import json
import os
import time

import numpy as np
import pytest

from sboxgates_tpu.core import canon
from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import GATES, NO_GATE, State
from sboxgates_tpu.graph.xmlio import state_to_xml
from sboxgates_tpu.resilience import faults
from sboxgates_tpu.resilience.deadline import DeadlineConfig
from sboxgates_tpu.search import Options, SearchContext
from sboxgates_tpu.search.orchestrator import (
    generate_graph_one_output,
    make_targets,
)
from sboxgates_tpu.search.serve import DONE, ServeJob, ServeOrchestrator
from sboxgates_tpu.store import ResultStore, rewrite_state
from sboxgates_tpu.store.store import _rebind
from sboxgates_tpu.utils.sbox import load_sbox

DATA = os.path.join(os.path.dirname(__file__), "data")
DES = os.path.join(DATA, "des_s1.txt")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.disarm()
    yield
    faults.disarm()


def random_table(rng, n):
    bits = np.zeros(tt.TABLE_BITS, dtype=bool)
    bits[: 1 << n] = rng.integers(0, 2, 1 << n).astype(bool)
    return tt.from_bits(bits)


def random_transform(rng, n):
    return canon.Transform(
        tuple(int(v) for v in rng.permutation(n)),
        tuple(int(v) for v in rng.integers(0, 2, n)),
        int(rng.integers(0, 2)),
    )


def random_circuit(n, n_gates, seed):
    r = np.random.default_rng(seed)
    st = State.init_inputs(n)
    for _ in range(n_gates):
        kind = r.integers(0, 3)
        if kind == 0:
            a, b = r.choice(st.num_gates, 2, replace=False)
            st.add_gate(int(r.integers(1, 15)), int(a), int(b), GATES)
        elif kind == 1 and st.num_gates >= 3:
            a, b, c = r.choice(st.num_gates, 3, replace=False)
            st.add_lut(int(r.integers(1, 256)), int(a), int(b), int(c))
        else:
            st.add_not_gate(int(r.integers(0, st.num_gates)), GATES)
    st.outputs[0] = st.num_gates - 1
    return st


def xml_digests(d):
    return {
        f: hashlib.sha256(
            open(os.path.join(d, f), "rb").read()
        ).hexdigest()
        for f in sorted(os.listdir(d)) if f.endswith(".xml")
    }


# -- canonicalization ------------------------------------------------------


def test_transform_algebra_compose_invert():
    """apply/compose/invert form a group action on tables."""
    rng = np.random.default_rng(0)
    for n in (3, 5, 8):
        dom = 1 << n
        for _ in range(12):
            T = random_table(rng, n)
            t1, t2 = random_transform(rng, n), random_transform(rng, n)
            a = canon.apply_transform(canon.compose(t2, t1), T)
            b = canon.apply_transform(t2, canon.apply_transform(t1, T))
            assert np.array_equal(a, b)
            ident = canon.compose(canon.invert(t1), t1)
            assert ident.is_identity()
            masked_bits = tt.to_bits(T).copy()
            masked_bits[dom:] = False
            assert np.array_equal(
                canon.apply_transform(ident, T),
                tt.from_bits(masked_bits),
            )


def test_canonical_key_frame_invariant():
    """THE property gate: random truth tables under random input
    permutations/negations and output complement all map to one
    canonical key, and the returned transforms map every frame to the
    SAME canonical table."""
    rng = np.random.default_rng(1)
    for n in (3, 4, 6, 8):
        mask = tt.mask_table(n)
        for _ in range(4):
            T = random_table(rng, n)
            key0, tr0 = canon.canonicalize(T, mask, GATES)
            assert tr0 is not None
            canon0 = canon.apply_transform(tr0, T & mask)
            for _ in range(5):
                g = random_transform(rng, n)
                T2 = canon.apply_transform(g, T)
                key2, tr2 = canon.canonicalize(T2, mask, GATES)
                assert key2 == key0
                assert np.array_equal(
                    canon.apply_transform(tr2, T2 & mask), canon0
                )
            # Determinism: a literal repeat returns the same transform,
            # so the composed hit rewrite is the identity.
            key3, tr3 = canon.canonicalize(T.copy(), mask, GATES)
            assert key3 == key0 and tr3 == tr0


def test_canonical_key_ignores_dont_care_bits():
    """Bits outside the mask never enter the key (a don't-care scribble
    is the same query)."""
    rng = np.random.default_rng(2)
    mask = tt.mask_table(4)
    T = random_table(rng, 4)
    key0, _ = canon.canonicalize(T, mask, GATES)
    bits = tt.to_bits(T).copy()
    bits[16:] = rng.integers(0, 2, tt.TABLE_BITS - 16).astype(bool)
    key1, _ = canon.canonicalize(tt.from_bits(bits), mask, GATES)
    assert key1 == key0
    # The metric is part of the key: GATES and SAT entries never mix.
    key_sat, _ = canon.canonicalize(T, mask, 1)
    assert key_sat != key0


def test_symmetric_orbit_falls_back_to_exact_key():
    """A fully symmetric table (XOR of all 8 inputs) blows the
    candidate cap; canonicalize falls back to the exact-digest key
    (deterministic, identity-frame only) instead of a multi-second
    group scan — and the decision is orbit-invariant, so it can never
    split a key."""
    idx = np.arange(256)
    bits = np.zeros(256, dtype=bool)
    for i in range(8):
        bits ^= ((idx >> i) & 1).astype(bool)
    T = tt.from_bits(bits)
    t0 = time.perf_counter()
    key, tr = canon.canonicalize(T, tt.mask_table(8), GATES)
    assert time.perf_counter() - t0 < 1.0
    assert tr is None and key.startswith("x")
    key2, tr2 = canon.canonicalize(T, tt.mask_table(8), GATES)
    assert (key2, tr2) == (key, None)


def test_rewrite_state_verified_over_all_inputs():
    """Circuit rewrite under a random transform realizes exactly the
    transformed table on ALL 2^8 inputs; the identity transform
    reproduces the stored graph byte-for-byte."""
    rng = np.random.default_rng(3)
    for n in (3, 5, 8):
        mask = tt.mask_table(n)
        for s in range(6):
            st = random_circuit(n, 6, 100 * n + s)
            T = st.tables[st.outputs[0]]
            t = random_transform(rng, n)
            st2 = rewrite_state(st, t)
            want = canon.apply_transform(t, T & mask)
            got = st2.tables[st2.outputs[0]]
            # Explicit all-2^8-inputs comparison under the mask.
            assert np.array_equal(
                tt.to_bits(got) & tt.to_bits(mask),
                tt.to_bits(want) & tt.to_bits(mask),
            )
            ident = rewrite_state(st, canon.identity_transform(n))
            assert state_to_xml(ident) == state_to_xml(st)


# -- the store -------------------------------------------------------------


def test_store_roundtrip_equivalent_frames_and_keep_first(tmp_path):
    """put + get round trip: an exact repeat returns the stored graph
    byte-identically; an equivalent-frame query gets a rewritten,
    re-verified circuit; the first publisher of a key wins."""
    store = ResultStore(str(tmp_path / "s"), sync=True)
    st = random_circuit(5, 8, 42)
    mask = tt.mask_table(5)
    T = st.tables[st.outputs[0]].copy()
    store.put_state(st, T, mask, GATES)
    kind, hit = store.fetch(T, mask, GATES)
    assert kind == "hit" and hit.exact_frame
    assert state_to_xml(hit.state) == state_to_xml(
        _rebind(st, st.outputs[0])
    )
    # Equivalent frame: rewritten + verified.
    g = canon.Transform((4, 2, 0, 1, 3), (1, 0, 1, 0, 0), 1)
    T2 = canon.apply_transform(g, T)
    kind, hit2 = store.fetch(T2, mask, GATES)
    assert kind == "hit" and not hit2.exact_frame
    out = hit2.state.tables[hit2.state.outputs[0]]
    assert bool(tt.eq_mask(out, T2, mask))
    # Keep-first: a second publisher of the same key is a no-op.
    other = random_circuit(5, 4, 7)
    other.outputs[0] = other.num_gates - 1
    before = open(store._path(hit.key)).read()
    store.put_state(other, T, mask, GATES)
    assert open(store._path(hit.key)).read() == before
    # Unknown query: a miss, counted as such.
    kind, none = store.fetch(
        np.full(8, 0x1234, np.uint32), mask, GATES
    )
    assert kind == "miss" and none is None
    store.close()


def test_corrupt_entries_quarantined_as_miss(tmp_path):
    """A truncated, digest-corrupt, or garbage entry is a MISS and is
    moved to quarantine/ — never a crash, never a wrong answer."""
    reg_ctx = SearchContext(Options(seed=1))
    store = ResultStore(
        str(tmp_path / "s"), stats=reg_ctx.stats, sync=True
    )
    mask = tt.mask_table(5)
    sts, keys, seed = [], [], 0
    while len(sts) < 3:  # seeds whose canonical keys are distinct
        seed += 1
        st = random_circuit(5, 6, seed)
        key = canon.canonicalize(
            st.tables[st.outputs[0]], mask, GATES
        )[0]
        if key in keys:
            continue
        sts.append(st)
        keys.append(key)
        store.put_state(st, st.tables[st.outputs[0]], mask, GATES)
    # Truncate one, flip a digest byte in another, garbage the third.
    p0, p1, p2 = (store._path(k) for k in keys)
    torn = open(p0).read()[:40]
    open(p0, "w").write(torn)
    doc = json.load(open(p1))
    doc["sha256"] = ("0" * 8) + doc["sha256"][8:]
    json.dump(doc, open(p1, "w"))
    open(p2, "w").write("not json at all")
    for st in sts:
        kind, val = store.fetch(
            st.tables[st.outputs[0]], mask, GATES
        )
        assert kind == "miss" and val is None
    qdir = tmp_path / "s" / "quarantine"
    assert len(os.listdir(qdir)) == 3
    assert int(reg_ctx.stats["store_corrupt_quarantined"]) == 3
    assert int(reg_ctx.stats["store_misses"]) == 3
    assert reg_ctx.stats.undeclared() == set()
    store.close()


def test_unknown_entry_version_is_plain_miss_not_quarantine(tmp_path):
    """A future-build entry version reads as a MISS without quarantine:
    stores are shared across builds, and an older reader must never
    destroy an entry a newer build can still use."""
    store = ResultStore(str(tmp_path / "s"), sync=True)
    st = random_circuit(5, 6, 4)
    mask = tt.mask_table(5)
    T = st.tables[st.outputs[0]]
    store.put_state(st, T, mask, GATES)
    key = canon.canonicalize(T, mask, GATES)[0]
    path = store._path(key)
    doc = json.load(open(path))
    doc["v"] = 99
    json.dump(doc, open(path, "w"))
    kind, val = store.fetch(T, mask, GATES)
    assert kind == "miss" and val is None
    assert os.path.exists(path)  # untouched, not quarantined
    assert not os.path.exists(tmp_path / "s" / "quarantine")
    store.close()


def test_rewrite_shared_output_gate_complements_both_bits():
    """Two output bits bound to the SAME gate under an output
    complement: the in-place function flip is refused (it would invert
    the second bit's view) and both bits come back correct."""
    st = random_circuit(3, 4, 21)
    gid = st.outputs[0]
    st.outputs[1] = gid
    t = canon.Transform((0, 1, 2), (0, 0, 0), 1)
    out = rewrite_state(st, t)
    mask = tt.mask_table(3)
    for bit in (0, 1):
        got = out.tables[out.outputs[bit]]
        want = canon.apply_transform(t, st.tables[gid] & mask)
        assert np.array_equal(
            tt.to_bits(got) & tt.to_bits(mask),
            tt.to_bits(want) & tt.to_bits(mask),
        ), bit


def test_store_fault_sites_degrade(tmp_path):
    """Injected ``store.get`` / ``store.put`` / ``store.index`` raises
    degrade to miss / skipped publish / skipped index line — the
    search path never sees an exception."""
    store = ResultStore(str(tmp_path / "s"), sync=True)
    st = random_circuit(5, 6, 9)
    mask = tt.mask_table(5)
    T = st.tables[st.outputs[0]]
    faults.arm("store.put", "raise", "1")
    store.put_state(st, T, mask, GATES)  # injected: publish skipped
    faults.disarm()
    assert store.fetch(T, mask, GATES)[0] == "miss"
    faults.arm("store.index", "raise", "1+")
    store.put_state(st, T, mask, GATES)  # index append skipped, entry lands
    faults.disarm()
    assert not os.path.exists(tmp_path / "s" / "index.jsonl")
    assert store.fetch(T, mask, GATES)[0] == "hit"
    faults.arm("store.get", "raise", "1")
    kind, val = store.fetch(T, mask, GATES)  # injected: miss
    assert kind == "miss" and val is None
    faults.disarm()
    assert store.fetch(T, mask, GATES)[0] == "hit"
    store.close()


def test_object_dirs_sorted_for_deterministic_sweeps(tmp_path):
    """Shard traversal (index rebuilds, stale-tmp sweeps) visits
    objects/ subdirectories in sorted order, not filesystem enumeration
    order (regression for the unsorted os.listdir R11 flagged)."""
    store = ResultStore(str(tmp_path / "s"), sync=True)
    base = os.path.join(str(tmp_path / "s"), "objects")
    for shard in ("ff", "00", "7a"):
        os.makedirs(os.path.join(base, shard), exist_ok=True)
    dirs = [os.path.basename(d) for d in store._object_dirs()]
    assert dirs == sorted(dirs)
    assert {"00", "7a", "ff"} <= set(dirs)


def test_unwritable_store_degrades_readonly(tmp_path):
    """An unwritable store directory degrades to read-only mode (the
    logged-note contract): construction never raises, publishes become
    no-ops, and lookups against a populated read-only store keep
    working."""
    # An unwritable root (a plain file where the directory should be):
    # construction degrades instead of raising.
    bad = tmp_path / "not-a-dir"
    bad.write_text("occupied")
    store = ResultStore(str(bad))
    assert store.readonly
    assert store._thread is None  # no writer thread in ro mode
    st = random_circuit(4, 5, 11)
    mask = tt.mask_table(4)
    T = st.tables[st.outputs[0]]
    store.put_state(st, T, mask, GATES)  # silent no-op
    assert store.fetch(T, mask, GATES)[0] == "miss"
    # Explicit read-only mode over a populated store: lookups hit,
    # publishes stay no-ops.
    d = str(tmp_path / "ro")
    ResultStore(d, sync=True).put_state(st, T, mask, GATES)
    ro = ResultStore(d, readonly=True)
    assert ro.fetch(T, mask, GATES)[0] == "hit"
    skey = canon.canonicalize(T, mask, GATES)[0]
    seed, okey, other = 11, skey, st
    while okey == skey:  # a circuit in a DIFFERENT canonical class
        seed += 1
        other = random_circuit(4, 5, seed)
        okey = canon.canonicalize(
            other.tables[other.outputs[0]], mask, GATES
        )[0]
    ro.put_state(other, other.tables[other.outputs[0]], mask, GATES)
    assert ro.status_view()["readonly"]
    assert not os.path.exists(ro._path(okey))  # ro handle never wrote


def test_lut_sub_tables_published_as_shared_entries(tmp_path):
    """ReducedLUT-style sharing: publishing a LUT circuit also
    publishes its decomposition sub-tables (cones of >= 2 gates), so a
    later query for just the sub-function — in any equivalent frame —
    hits."""
    store = ResultStore(str(tmp_path / "s"), sync=True)
    st = State.init_inputs(3)
    g3 = st.add_lut(0x96, 0, 1, 2)
    g4 = st.add_lut(0xE8, g3, 1, 2)
    g5 = st.add_lut(0xCA, g4, 0, 2)
    st.outputs[0] = g5
    mask = tt.mask_table(3)
    store.put_state(
        st, st.tables[g5], mask, GATES, sub_tables=True
    )
    # The inner cone (g4 over g3) is its own shared entry now.
    sub_target = st.tables[g4]
    rng = np.random.default_rng(5)
    g = canon.Transform(
        tuple(int(v) for v in rng.permutation(3)), (1, 0, 1), 1
    )
    q = canon.apply_transform(g, sub_target)
    kind, hit = store.fetch(q, mask, GATES)
    assert kind == "hit"
    out = hit.state.tables[hit.state.outputs[0]]
    assert bool(tt.eq_mask(out, q, mask))
    assert hit.meta.get("sub_table") is True
    store.close()


# -- serve integration -----------------------------------------------------

#: Device-dispatch options (mirrors tests/test_serve.py DEVOPTS): node
#: heads dispatch to the (CPU) device, so the zero-dispatch hit gate is
#: meaningful.
DEVOPTS = dict(
    seed=11, lut_graph=True, randomize=False, host_small_steps=False,
    native_engine=False, warmup=False,
)


def _toy_files(tmp_path, n):
    from sboxgates_tpu.search.fleet import toy_fleet_boxes

    d = tmp_path / "boxes"
    os.makedirs(d, exist_ok=True)
    paths = []
    for i, bj in enumerate(toy_fleet_boxes(n)):
        p = str(d / f"toy{i}.txt")
        with open(p, "w") as f:
            f.write(" ".join("%02x" % v for v in bj.sbox[:8]))
        paths.append(p)
    return paths


def _serve_run(tmp_path, sub, store_dir, paths, output, **opts):
    ctx = SearchContext(Options(**{
        **DEVOPTS, **opts, "result_store": store_dir,
    }))
    orch = ServeOrchestrator(
        ctx, str(tmp_path / sub), lanes=4,
        deadline=DeadlineConfig(retries=2, backoff_s=0.01),
        log=lambda s: None,
    )
    jobs = [
        orch.submit(ServeJob(job_id=f"t{i}", sbox_path=p, output=output))
        for i, p in enumerate(paths)
    ]
    orch.start()
    view = orch.run_until_idle(timeout_s=240)
    orch.stop()
    ctx.result_store.flush()
    return ctx, orch, view, jobs


def test_serve_repeat_query_zero_dispatch_bit_identical(tmp_path):
    """THE acceptance gate: a repeated serve-mode query is served from
    the store with ZERO device dispatches and a circuit bit-identical
    to the one the fresh search produced, with the hit visible in the
    queue view (the job skips the queue)."""
    store_dir = str(tmp_path / "store")
    paths = _toy_files(tmp_path, 4)
    ctx1, orch1, v1, _ = _serve_run(
        tmp_path, "cold", store_dir, paths, 0
    )
    assert v1["counts"][DONE] == 4, v1
    assert int(ctx1.stats["device_dispatches"]) > 0
    assert int(ctx1.stats["store_misses"]) == 4
    assert int(ctx1.stats["store_puts"]) >= 1
    ctx2, orch2, v2, jobs2 = _serve_run(
        tmp_path, "warm", store_dir, paths, 0
    )
    assert v2["counts"][DONE] == 4, v2
    assert int(ctx2.stats["store_hits"]) == 4
    assert int(ctx2.stats.get("device_dispatches", 0)) == 0
    assert ctx2.stats.histograms()["store_get_s"]["count"] >= 4
    for j in jobs2:
        row = v2["jobs"][j.job_id]
        assert row["store"] == "hit"
        assert "queue_wait_s" not in row  # never entered the queue
        d_cold = xml_digests(os.path.join(orch1.root, j.job_id))
        d_warm = xml_digests(os.path.join(orch2.root, j.job_id))
        assert len(d_warm) == 1
        (fname, digest), = d_warm.items()
        assert d_cold.get(fname) == digest, (j.job_id, fname)
        # The hit job's journal reads as a completed run.
        recs = [
            json.loads(line) for line in
            open(os.path.join(orch2.root, j.job_id,
                              "search.journal.jsonl"))
        ]
        assert recs[0]["config"]["store"] == "hit"
        assert recs[-1]["type"] == "run_done"
    assert v2["store"]["hits"] == 4
    assert ctx2.stats.undeclared() == set()


def test_serve_all_outputs_repeat_hits_exact_key(tmp_path):
    """All-outputs queries key exactly (no canonical merge) and repeat
    across tenants with zero dispatches."""
    store_dir = str(tmp_path / "store")
    paths = _toy_files(tmp_path, 2)
    ctx1, orch1, v1, _ = _serve_run(
        tmp_path, "cold", store_dir, paths, -1
    )
    assert v1["counts"][DONE] == 2, v1
    ctx2, orch2, v2, _ = _serve_run(
        tmp_path, "warm", store_dir, paths, -1
    )
    assert v2["counts"][DONE] == 2, v2
    assert int(ctx2.stats["store_hits"]) == 2
    assert int(ctx2.stats.get("device_dispatches", 0)) == 0
    for i in range(2):
        d_cold = xml_digests(os.path.join(orch1.root, f"t{i}"))
        d_warm = xml_digests(os.path.join(orch2.root, f"t{i}"))
        (fname, digest), = d_warm.items()
        assert d_cold.get(fname) == digest


def test_drained_frontier_resumes_across_processes(tmp_path):
    """The partial-hit acceptance gate: a drained serve run publishes
    its interrupted jobs' frontiers; a NEW orchestrator in a DIFFERENT
    root (same seeds) seeds from the store and finishes bit-identically
    to an uninterrupted run — the PR 3 resume contract applied across
    processes via the store."""
    store_dir = str(tmp_path / "store")
    ctx1 = SearchContext(Options(
        seed=11, iterations=4, result_store=store_dir,
    ))
    orch1 = ServeOrchestrator(
        ctx1, str(tmp_path / "r1"), lanes=1,
        deadline=DeadlineConfig(retries=3, backoff_s=5.0),
        log=lambda s: None,
    )
    faults.arm("serve.preempt@job:j0", "raise", "2")
    j0 = orch1.submit(ServeJob(job_id="j0", sbox_path=DES, output=0))
    orch1.start()
    t0 = time.monotonic()
    while time.monotonic() - t0 < 60:
        if orch1.status_view()["jobs"]["j0"].get("preemptions", 0):
            break
        time.sleep(0.02)
    faults.disarm()
    orch1.drain(timeout_s=30)
    ctx1.result_store.flush()
    assert int(ctx1.stats["store_puts"]) >= 1

    ctx2 = SearchContext(Options(
        seed=11, iterations=4, result_store=store_dir,
    ))
    orch2 = ServeOrchestrator(
        ctx2, str(tmp_path / "r2"), lanes=1,
        deadline=DeadlineConfig(retries=2, backoff_s=0.01),
        log=lambda s: None,
    )
    j0b = orch2.submit(ServeJob(job_id="j0", sbox_path=DES, output=0))
    assert j0b.store == "partial"
    assert int(ctx2.stats["store_partial_hits"]) == 1
    orch2.start()
    v2 = orch2.run_until_idle(timeout_s=120)
    orch2.stop()
    assert v2["counts"][DONE] == 1, v2
    assert v2["jobs"]["j0"]["store"] == "partial"

    ref_dir = str(tmp_path / "ref")
    os.makedirs(ref_dir)
    ctx3 = SearchContext(Options(seed=int(j0b.seed), iterations=4))
    sbox, n = load_sbox(DES, 0)
    st = State.init_inputs(n)
    generate_graph_one_output(
        ctx3, st, make_targets(sbox), 0, save_dir=ref_dir,
        log=lambda s: None, journal=None,
    )
    assert xml_digests(os.path.join(orch2.root, "j0")) == \
        xml_digests(ref_dir)


def test_store_get_job_targeted_fault_degrades_one_tenant(tmp_path):
    """``store.get@job:ID``: the injected lookup fault fires only on
    the targeted tenant's admission consult — that job degrades to
    miss-and-search while its neighbors keep hitting."""
    store_dir = str(tmp_path / "store")
    paths = _toy_files(tmp_path, 4)
    _serve_run(tmp_path, "cold", store_dir, paths, 0)
    faults.arm("store.get@job:t1", "raise", "1+")
    ctx, orch, view, jobs = _serve_run(
        tmp_path, "warm", store_dir, paths, 0
    )
    assert view["counts"][DONE] == 4, view
    assert view["jobs"]["t1"].get("store") is None  # searched normally
    assert int(ctx.stats["store_hits"]) == 3
    assert int(ctx.stats["store_misses"]) == 1
    for jid in ("t0", "t2", "t3"):
        assert view["jobs"][jid]["store"] == "hit"


def test_watch_renders_store_section():
    """The serve queue view surfaces store outcomes: head counters and
    per-job store=hit rows (cache-hit jobs visibly skip the queue)."""
    from sboxgates_tpu.telemetry.watch import render_serve

    serve = {
        "lanes": 2, "lane_bucket": 2, "merge": True, "waves": 0,
        "draining": False,
        "counts": {"queued": 0, "running": 0, "preempted": 0,
                   "quarantined": 0, "done": 2},
        "store": {"hits": 1, "misses": 1, "partial_hits": 0,
                  "puts": 1, "readonly": False},
        "jobs": {
            "a": {"state": "done", "tenant": "t", "priority": 0,
                  "bucket": 2, "failures": 0, "preemptions": 0,
                  "store": "hit", "ttfh_s": 0.001},
            "b": {"state": "done", "tenant": "t", "priority": 0,
                  "bucket": 2, "failures": 0, "preemptions": 0},
        },
    }
    text = "\n".join(render_serve(serve))
    assert "store hit=1/part=0/miss=1" in text
    assert "store=hit" in text
