/* Test-only golden-interop shim.
 *
 * Compiles the REFERENCE implementation's state serialization (the
 * fingerprint, save-file naming, and XML writer from
 * /root/reference/state.c, truncated above its libxml-based loader by the
 * test fixture — the truncated source is generated into the build temp
 * dir at test time and never enters this repository) and exports plain-C
 * wrappers so tests can assert byte-exact fingerprint/filename/XML parity
 * against sboxgates_tpu.graph.xmlio.  See tests/test_golden_interop.py.
 */

#define NO_MPI_HEADER 1

#include <stdint.h>

/* Referenced by the truncated TU's generate_target (unused by the
 * functions under test). */
uint8_t g_sbox_enc[256];

#include "state_trunc.c"

uint32_t golden_fingerprint(const state *st) { return state_fingerprint(*st); }

void golden_save(const state *st) { save_state(*st); }

int golden_sat_metric(int gate_type) { return get_sat_metric(gate_type); }

uint64_t golden_sizeof_state(void) { return sizeof(state); }

uint64_t golden_sizeof_gate(void) { return sizeof(gate); }
