"""Compile-latency subsystem tests: the kernel warmup registry, the
background KernelWarmer, the device-resident table cache, and the
persistent compile-cache wiring.

The headline property (ISSUE 5 acceptance): with the warmer having run,
a search crossing a ``bucket_size`` boundary performs ZERO steady-state
compiles — asserted under a strict ``recompile_guard``.  Results are
bit-identical with warmup on or off (the warmed path calls the same
lowered executable the lazy path would build).
"""

import os

import numpy as np
import pytest

from planted import build_planted_lut5_small
from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import GATES, NO_GATE, State
from sboxgates_tpu.resilience import faults
from sboxgates_tpu.search import Options, SearchContext, warmup
from sboxgates_tpu.search.kwan import create_circuit
from sboxgates_tpu.search.lut import lut3_search
from sboxgates_tpu.utils import recompile_guard


def _grow_state(g: int, seed: int = 5) -> State:
    rng = np.random.default_rng(seed)
    st = State.init_inputs(8)
    while st.num_gates < g:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    return st


def _unrealizable_target() -> np.ndarray:
    # A random 256-bit function is (overwhelmingly) not any single
    # 3-LUT of XOR-chain tables, so the sweeps scan the whole space.
    return np.asarray(
        np.random.default_rng(99).integers(0, 2**32, size=8),
        dtype=np.uint32,
    )


def _warm_ctx(monkeypatch, **opt_kwargs) -> SearchContext:
    monkeypatch.setenv("SBG_WARMUP", "1")
    opt_kwargs.setdefault("lut_graph", True)
    opt_kwargs.setdefault("randomize", False)
    opt_kwargs.setdefault("host_small_steps", False)
    ctx = SearchContext(Options(seed=7, **opt_kwargs))
    assert ctx.warmer is not None and ctx.warmer.enabled
    return ctx


# -------------------------------------------------------------------------
# Tentpole: zero compiles across a bucket transition
# -------------------------------------------------------------------------


def test_bucket_transition_zero_steady_state_compiles(monkeypatch):
    """Entering bucket 64 schedules the bucket-512 warm set; after the
    warmer finishes, the first dispatch past the boundary is served by
    the AOT executable — no tracing, no compiling, proven by a strict
    process-wide recompile_guard."""
    ctx = _warm_ctx(monkeypatch)
    st = _grow_state(63)
    target, mask = _unrealizable_target(), tt.mask_table(8)
    # Earlier tests (the fleet suite runs first) may have populated the
    # process-wide warm cache with this bucket's specs; drop it so the
    # compiled-count assertions below measure THIS schedule.
    warmup.drop_warm_cache()
    try:
        # Bucket-64 dispatch: triggers warm scheduling for bucket 512.
        lut3_search(ctx, st, target, mask, [])
        assert ctx.warmer.wait_idle(300), "warmer never went idle"
        ws = ctx.warmup_stats()
        assert ws["warm_compiled"] >= 2, ws
        assert ws["warm_failed"] == 0, ws

        st2 = _grow_state(65)
        with recompile_guard(allowed=0, label="bucket transition") as rep:
            lut3_search(ctx, st2, target, mask, [])
        assert rep.compiles == 0
        assert ctx.stats["warm_hits"] >= 1
        assert ctx.warmup_stats().get("warm_aval_mismatches", 0) == 0
    finally:
        ctx.warmer.shutdown()


def test_prewarm_covers_current_bucket(monkeypatch):
    """prewarm(g) builds gate count g's OWN kernel set (the restart /
    --resume-run shape): the very first dispatch is then compile-free."""
    ctx = _warm_ctx(monkeypatch)
    st = _grow_state(24)
    target, mask = _unrealizable_target(), tt.mask_table(8)
    try:
        ctx.warmer.prewarm(st.num_gates)
        assert ctx.warmer.wait_idle(300)
        with recompile_guard(allowed=0, label="prewarmed first dispatch") \
                as rep:
            lut3_search(ctx, st, target, mask, [])
        assert rep.compiles == 0
        assert ctx.stats["warm_hits"] >= 1
    finally:
        ctx.warmer.shutdown()


# -------------------------------------------------------------------------
# Registry parity: live dispatches == warm specs
# -------------------------------------------------------------------------


def test_registry_parity_dispatches_are_warmable(monkeypatch):
    """Every jitted entry the drivers dispatch must be present in the
    warmup registry with matching static args — and, for warmable
    kernels, the exact (statics, avals) signature must appear in
    warm_specs for the dispatching gate count, or the warmer would build
    executables the drivers never hit."""
    from sboxgates_tpu.search import context as ctxmod

    observed = []
    orig = ctxmod.SearchContext.kernel_call

    def recording(self, name, statics, args, g=None):
        observed.append(
            (name, dict(statics), warmup.arg_signature(args), g)
        )
        return orig(self, name, statics, args, g=g)

    monkeypatch.setattr(ctxmod.SearchContext, "kernel_call", recording)

    st, target, mask = build_planted_lut5_small()
    ctx = SearchContext(Options(
        seed=3, lut_graph=True, randomize=False, host_small_steps=False,
        native_engine=False,
    ))
    out = create_circuit(ctx, st.copy(), target, mask, [])
    assert out != NO_GATE

    # A gate-mode node too, so gate_step_stream is covered.
    gctx = SearchContext(Options(
        seed=3, randomize=False, host_small_steps=False,
        native_engine=False,
    ))
    gctx.gate_step(st, target, mask)

    assert observed, "no registry dispatches recorded"
    plans = {
        True: warmup.WarmPlan.from_context(ctx),
        False: warmup.WarmPlan.from_context(gctx),
    }
    seen_names = set()
    for name, statics, sig, g in observed:
        d = warmup.KERNELS[name]
        seen_names.add(name)
        assert set(statics) <= set(d.static_names), (name, statics)
        if not d.warmable or g is None:
            continue
        plan = plans[name != "gate_step_stream"]
        keys = {s.key for s in warmup.warm_specs(plan, g)}
        key = (name, tuple(sorted(statics.items())), sig)
        assert key in keys, (
            f"dispatch {name} g={g} statics={statics} sig={sig} absent "
            f"from warm_specs — live call sites and the registry drifted"
        )
    assert "lut_step_stream" in seen_names
    assert "gate_step_stream" in seen_names


def test_registry_rejects_unknown_statics():
    with pytest.raises(TypeError, match="does not take static args"):
        warmup.kernel("lut3_stream", {"bogus": 1})


def test_warm_specs_enumerate_expected_set():
    plan = warmup.WarmPlan(
        lut_graph=True, has_not=False,
        pair_table=((256,), "int16"), not_table=None,
        triple_table=((65536,), "int16"),
    )
    names = [s.name for s in warmup.warm_specs(plan, 65)]
    # Bucket-512 entry point: fused head, standalone 3-LUT stream, the
    # staged 7-LUT feasible stream, and the stage-B solver.  The 5-LUT
    # space at g=65 is pivot-sized (not bucket-warmable), so no
    # lut5_stream.
    assert "lut_step_stream" in names
    assert "lut3_stream" in names
    assert "feasible_stream" in names
    assert "lut7_solve" in names
    assert "lut5_stream" not in names
    gate_plan = warmup.WarmPlan(
        lut_graph=False, has_not=False,
        pair_table=((256,), "int16"), not_table=None,
        triple_table=((65536,), "int16"),
    )
    assert [s.name for s in warmup.warm_specs(gate_plan, 65)] == [
        "gate_step_stream"
    ]


# -------------------------------------------------------------------------
# Bucket-keyed pivot kernels (ISSUE 6 satellite: registered AND warmable)
# -------------------------------------------------------------------------


def test_pivot_shapes_key_on_bucket():
    """Pivot operand shapes are bucket functions: stable for every g in
    a pivot bucket and every exclusion list, and the tile shape keeps
    the measured 128 boundary (a bucket edge)."""
    from sboxgates_tpu.ops import sweeps
    from sboxgates_tpu.search.lut import (
        pivot_g_bucket,
        pivot_padded_shapes,
        pivot_tile_shape,
    )

    assert pivot_tile_shape(50) == (256, 512)
    assert pivot_tile_shape(128) == (256, 512)
    assert pivot_tile_shape(129) == (512, 512)
    assert pivot_g_bucket(50) == pivot_g_bucket(64) == 64
    tl, th = pivot_tile_shape(50)
    assert pivot_padded_shapes(50, tl, th) == pivot_padded_shapes(64, tl, th)
    # the pad covers the worst case in the bucket: the real descriptor
    # count at the bucket top, exclusion-free
    _, tpad = pivot_padded_shapes(50, tl, th)
    assert tpad >= sweeps.pivot_tile_count(64, tl, th)
    assert sweeps.pivot_tile_count(64, tl, th) == (
        sweeps.pivot_tile_descs(64, tl, th).shape[0]
    )


def test_pivot_sweep_warm_zero_compiles(monkeypatch):
    """A prewarmed pivot-sized 5-LUT sweep — the kernels PR 5 left
    registered-but-unwarmable — dispatches with zero compiles under a
    strict process-wide recompile_guard, and finds the planted
    decomposition through the warmed executables."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from planted import build_planted_lut5

    from sboxgates_tpu.search.lut import _lut5_search_pivot

    st, target, mask = build_planted_lut5()
    ctx = _warm_ctx(monkeypatch, native_engine=False)
    try:
        ctx.warmer.prewarm(st.num_gates)
        assert ctx.warmer.wait_idle(300), "warmer never went idle"
        assert ctx.warmup_stats()["warm_failed"] == 0
        # First sweep triggers next-bucket scheduling; drain those
        # background compiles before the strict guard.
        res1 = _lut5_search_pivot(ctx, st, target, mask, [])
        assert res1 is not None
        assert ctx.warmer.wait_idle(300)
        h0 = ctx.stats["warm_hits"]
        with recompile_guard(allowed=0, label="warmed pivot sweep") as rep:
            res2 = _lut5_search_pivot(ctx, st, target, mask, [])
        assert rep.compiles == 0
        assert res2 == res1
        assert ctx.stats["warm_hits"] >= h0 + 2  # pair cells + stream
    finally:
        ctx.warmer.shutdown()


# -------------------------------------------------------------------------
# Mesh-shaped warm specs (ISSUE 6 satellite: pinned-mesh AOT coverage)
# -------------------------------------------------------------------------


def test_mesh_warm_specs_cover_sharded_streams(monkeypatch):
    """A pinned single-process mesh gets a warmer whose sets are the
    sharded stream executables; the live sharded dispatch is served by
    the AOT build and results are identical to the lazy mesh path."""
    from sboxgates_tpu.parallel import MeshPlan, make_mesh
    from sboxgates_tpu.search.lut import lut3_search

    st = _grow_state(24)
    target, mask = _unrealizable_target(), tt.mask_table(8)

    monkeypatch.setenv("SBG_WARMUP", "0")
    lazy = SearchContext(
        Options(seed=7, lut_graph=True, randomize=False,
                host_small_steps=False, warmup=False),
        mesh_plan=MeshPlan(make_mesh()),
    )
    out_lazy = lut3_search(lazy, st.copy(), target, mask, [])

    monkeypatch.setenv("SBG_WARMUP", "1")
    ctx = SearchContext(
        Options(seed=7, lut_graph=True, randomize=False,
                host_small_steps=False),
        mesh_plan=MeshPlan(make_mesh()),
    )
    assert ctx.warmer is not None and ctx.warmer.enabled
    try:
        ctx.warmer.prewarm(st.num_gates)
        assert ctx.warmer.wait_idle(300)
        ws = ctx.warmup_stats()
        assert ws["warm_compiled"] >= 2 and ws["warm_failed"] == 0, ws
        from sboxgates_tpu.search import warmup as W

        hits = {"n": 0}
        orig = W.mesh_warm_lookup

        def spy(name, mesh, statics, args):
            r = orig(name, mesh, statics, args)
            if r is not None:
                hits["n"] += 1
            return r

        import sboxgates_tpu.parallel.mesh as M

        monkeypatch.setattr(
            M, "_mesh_warm_lookup",
            lambda name, mesh, statics, args: spy(name, mesh, statics, args),
        )
        out_warm = lut3_search(ctx, st.copy(), target, mask, [])
        assert out_warm == out_lazy
        assert hits["n"] >= 1, "sharded dispatch missed the warm cache"
    finally:
        ctx.warmer.shutdown()


# -------------------------------------------------------------------------
# Device-resident table cache
# -------------------------------------------------------------------------


def test_device_tables_cached_and_mutation_invalidates():
    st, _, _ = build_planted_lut5_small()
    ctx = SearchContext(Options(seed=1, lut_graph=True))
    t1 = ctx.device_tables(st)
    t2 = ctx.device_tables(st)
    assert t2 is t1
    assert ctx.stats["table_uploads"] == 1
    assert ctx.stats["table_cache_hits"] == 1
    # A value-copy has identical bytes: shares the upload.
    cp = st.copy()
    assert ctx.device_tables(cp) is t1
    # Mutation ALWAYS yields a fresh upload with the mutated content.
    cp.add_gate(bf.XOR, 0, 1, GATES)
    t3 = ctx.device_tables(cp)
    assert t3 is not t1
    np.testing.assert_array_equal(
        np.asarray(t3)[: cp.num_gates], cp.live_tables()
    )
    assert not np.asarray(t3)[cp.num_gates:].any()  # zero padding


def test_device_tables_mutation_property_sweep():
    """Property: any sequence of state mutations always produces a fresh
    upload whose device content equals the mutated live tables."""
    rng = np.random.default_rng(0)
    st = _grow_state(12)
    ctx = SearchContext(Options(seed=1, lut_graph=True))
    prev = ctx.device_tables(st)
    for _ in range(12):
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(
            int(rng.choice([bf.XOR, bf.AND, bf.OR])), int(a), int(b), GATES
        )
        cur = ctx.device_tables(st)
        assert cur is not prev
        np.testing.assert_array_equal(
            np.asarray(cur)[: st.num_gates], st.live_tables()
        )
        prev = cur
    ctx.invalidate_device_tables()
    assert ctx.device_tables(st) is not prev
    assert ctx.stats["table_uploads"] == 14


def test_device_tables_adoption_assignment_invalidates():
    """kwan's best-branch adoption assigns st.tables directly (no mutator
    runs); the content digest still sees it."""
    st = _grow_state(12)
    other = _grow_state(12, seed=9)
    ctx = SearchContext(Options(seed=1))
    t1 = ctx.device_tables(st)
    st.gates = other.gates
    st.tables = other.tables
    t2 = ctx.device_tables(st)
    assert t2 is not t1
    np.testing.assert_array_equal(
        np.asarray(t2)[: st.num_gates], st.live_tables()
    )


# -------------------------------------------------------------------------
# Bit-identical results with warmup on vs off
# -------------------------------------------------------------------------


def test_search_results_identical_warm_vs_lazy(monkeypatch):
    st0, target, mask = build_planted_lut5_small()

    def run(warm: bool):
        if warm:
            monkeypatch.setenv("SBG_WARMUP", "1")
        else:
            monkeypatch.setenv("SBG_WARMUP", "0")
        ctx = SearchContext(Options(
            seed=11, lut_graph=True, host_small_steps=False,
            native_engine=False, warmup=warm,
        ))
        st = st0.copy()
        if warm:
            # Exercise the ACTUAL warmed dispatch path, not just an idle
            # warmer: build this gate count's set first.
            ctx.warmer.prewarm(st.num_gates)
            assert ctx.warmer.wait_idle(300)
        out = create_circuit(ctx, st, target, mask, [])
        if warm:
            assert ctx.stats["warm_hits"] >= 1
            ctx.warmer.shutdown()
        return out, [
            (g.type, g.in1, g.in2, g.in3, g.function) for g in st.gates
        ]

    out_lazy, gates_lazy = run(False)
    out_warm, gates_warm = run(True)
    assert out_warm == out_lazy
    assert gates_warm == gates_lazy


# -------------------------------------------------------------------------
# Fault injection: a failed/hung background compile never hurts the search
# -------------------------------------------------------------------------


def test_warmup_compile_fault_degrades_to_lazy(monkeypatch):
    st0, target, mask = build_planted_lut5_small()
    baseline_ctx = SearchContext(Options(
        seed=11, lut_graph=True, randomize=False, host_small_steps=False,
        native_engine=False, warmup=False,
    ))
    st_base = st0.copy()
    out_base = create_circuit(baseline_ctx, st_base, target, mask, [])

    ctx = _warm_ctx(monkeypatch, native_engine=False)
    # The process-wide warm cache may hold these specs from earlier
    # tests; drop it so the worker actually reaches the fault site.
    warmup.drop_warm_cache()
    faults.arm("warmup.compile", "raise")
    try:
        ctx.warmer.prewarm(st0.num_gates)
        assert ctx.warmer.wait_idle(120)
        ws = ctx.warmup_stats()
        assert ws["warm_failed"] >= 1 and ws["warm_compiled"] == 0, ws
        st = st0.copy()
        out = create_circuit(ctx, st, target, mask, [])
        assert out == out_base
        assert [
            (g.type, g.in1, g.in2, g.in3, g.function) for g in st.gates
        ] == [
            (g.type, g.in1, g.in2, g.in3, g.function) for g in st_base.gates
        ]
    finally:
        faults.disarm("warmup.compile")
        ctx.warmer.shutdown()


def test_warmup_compile_hang_bounded_shutdown(monkeypatch):
    import time

    st0, target, mask = build_planted_lut5_small()
    ctx = _warm_ctx(monkeypatch, native_engine=False)
    warmup.drop_warm_cache()
    faults.arm("warmup.compile", "hang")
    try:
        ctx.warmer.prewarm(st0.num_gates)
        # The worker is parked in the hung compile; the search must not
        # notice (lazy compiles), and shutdown must return within its
        # deadline instead of joining forever.
        out = create_circuit(ctx, st0.copy(), target, mask, [])
        assert out != NO_GATE
        t0 = time.monotonic()
        ctx.warmer.shutdown(timeout=0.5)
        assert time.monotonic() - t0 < 5.0
    finally:
        faults.disarm("warmup.compile")


# -------------------------------------------------------------------------
# Persistent compile cache wiring
# -------------------------------------------------------------------------


def test_compile_cache_dir_resolution(monkeypatch):
    monkeypatch.delenv("SBG_COMPILE_CACHE", raising=False)
    assert warmup.compile_cache_dir(None, None) is None
    assert warmup.compile_cache_dir(None, "/runs/x") == os.path.join(
        "/runs/x", "xla_cache"
    )
    assert warmup.compile_cache_dir("/explicit", "/runs/x") == "/explicit"
    assert warmup.compile_cache_dir("", "/runs/x") is None  # explicit off
    monkeypatch.setenv("SBG_COMPILE_CACHE", "/envcache")
    assert warmup.compile_cache_dir(None, "/runs/x") == "/envcache"
    monkeypatch.setenv("SBG_COMPILE_CACHE", "")
    assert warmup.compile_cache_dir(None, "/runs/x") is None


def test_configure_compile_cache_applies_and_creates(tmp_path):
    import jax

    old = jax.config.jax_compilation_cache_dir
    try:
        target = str(tmp_path / "xla_cache")
        assert warmup.configure_compile_cache(target) == target
        assert os.path.isdir(target)
        assert jax.config.jax_compilation_cache_dir == target
        assert warmup.configure_compile_cache(None) is None
        # None leaves the previous configuration untouched.
        assert jax.config.jax_compilation_cache_dir == target
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


def test_sole_thread_rendezvous_takes_warm_path(monkeypatch):
    """With parallel_mux auto-on (the accelerator default) the context
    holds a Rendezvous(1); a sole live thread must still route head
    dispatches through the registry (warm lookup + compile telemetry) —
    only actual mux concurrency trades warm reuse for dispatch
    merging."""
    monkeypatch.setenv("SBG_WARMUP", "1")
    ctx = SearchContext(Options(
        seed=1, lut_graph=True, randomize=False, host_small_steps=False,
        parallel_mux=True,
    ))
    assert ctx.rdv is not None and ctx.rdv.live == 1
    assert ctx.warmer is not None
    st = _grow_state(24)
    try:
        ctx.lut_step(st, _unrealizable_target(), tt.mask_table(8), [])
        assert ctx.stats["warm_hits"] + ctx.stats["warm_misses"] >= 1
    finally:
        ctx.warmer.shutdown()


def test_warm_worker_retires_when_idle_and_respawns(monkeypatch):
    """The warm worker exits after WORKER_IDLE_EXIT_S on an empty queue
    (no parked-thread leak per context in long-lived processes), and a
    later schedule spawns a fresh one."""
    import time

    monkeypatch.setattr(warmup, "WORKER_IDLE_EXIT_S", 0.2)
    ctx = _warm_ctx(monkeypatch)
    try:
        ctx.warmer.prewarm(10)
        assert ctx.warmer.wait_idle(120)
        deadline = time.monotonic() + 10
        while ctx.warmer._thread is not None and time.monotonic() < deadline:
            time.sleep(0.05)
        assert ctx.warmer._thread is None, "idle worker never retired"
        ctx.warmer.prewarm(12)
        assert ctx.warmer.wait_idle(120), "retired worker was not respawned"
    finally:
        ctx.warmer.shutdown()
