"""Kill→resume property tests: a search killed at an arbitrary point and
restarted with --resume-run produces final circuits **bit-identical** to
an uninterrupted run with the same seed.

Tier-1 variant: three injected kill points — during a checkpoint write
(``ckpt.write``), between beam rounds (``search.round``), and mid-round
inside the node stream (``search.node``) — interrupted in-process via the
``raise`` fault action (same on-disk journal/checkpoint state as a crash,
without a fresh interpreter + jax import per case).  The full kill-point
matrix, with REAL ``os._exit`` crashes through the CLI subprocess, is
marked ``slow``.
"""

import hashlib
import os
import subprocess
import sys

import pytest

from sboxgates_tpu.cli import main
from sboxgates_tpu.resilience import faults
from sboxgates_tpu.resilience.faults import InjectedFault

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(ROOT, "tests", "data")
DES = os.path.join(DATA, "des_s1.txt")
FA = os.path.join(DATA, "crypto1_fa.txt")
SEED = "11"


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.disarm()
    yield
    faults.disarm()


def xml_digests(d):
    """{filename: sha256} of every checkpoint in a run directory."""
    return {
        f: hashlib.sha256(
            open(os.path.join(d, f), "rb").read()
        ).hexdigest()
        for f in sorted(os.listdir(d))
        if f.endswith(".xml")
    }


@pytest.fixture(scope="module")
def uninterrupted(tmp_path_factory):
    """One full-graph DES S1 run (4 beam rounds) — the bit-identity
    reference for every kill point."""
    d = str(tmp_path_factory.mktemp("des_ok"))
    assert main([DES, "--seed", SEED, "--output-dir", d]) == 0
    digests = xml_digests(d)
    assert digests, "reference run produced no checkpoints"
    return digests


# Kill points (site, hit): mid-checkpoint-write in round 2, between
# rounds 2 and 3, and mid-round-2 in the node stream.
KILL_POINTS = [
    ("ckpt.write", "3"),
    ("search.round", "2"),
    ("search.node", "7"),
]


@pytest.mark.parametrize("site,when", KILL_POINTS)
def test_killed_search_resumes_bit_identical(
    tmp_path, uninterrupted, site, when
):
    d = str(tmp_path)
    faults.arm(site, "raise", when)
    try:
        with pytest.raises(InjectedFault):
            main([DES, "--seed", SEED, "--output-dir", d])
    finally:
        faults.disarm()
    # The interrupted run must have stopped short of the full result.
    assert xml_digests(d).keys() != uninterrupted.keys() or site == "search.round"
    assert main(["--resume-run", d]) == 0
    assert xml_digests(d) == uninterrupted
    # Resuming the now-complete run is a no-op that exits 0.
    assert main(["--resume-run", d]) == 0


def test_one_output_driver_resumes_bit_identical(tmp_path):
    """Iteration-granular journal of generate_graph_one_output: kill in
    iteration 2's checkpoint write, resume, compare to uninterrupted."""
    ok = str(tmp_path / "ok")
    os.makedirs(ok)
    argv = [DES, "-o", "0", "-i", "2", "--seed", SEED]
    assert main(argv + ["--output-dir", ok]) == 0
    killed = str(tmp_path / "killed")
    os.makedirs(killed)
    faults.arm("ckpt.write", "raise", "2")
    try:
        with pytest.raises(InjectedFault):
            main(argv + ["--output-dir", killed])
    finally:
        faults.disarm()
    assert main(["--resume-run", killed]) == 0
    assert xml_digests(killed) == xml_digests(ok)


@pytest.mark.slow
def test_multibox_sweep_resumes_bit_identical(tmp_path):
    """Round-granular journal of the multibox lockstep driver.  Slow
    tier: the tier-1 kill points cover the single-box drivers and the
    journal machinery is shared; this adds the mb_round_done restore
    path over two boxes (one of them the full DES beam search)."""

    def digests(root):
        out = {}
        for sub in sorted(os.listdir(root)):
            p = os.path.join(root, sub)
            if os.path.isdir(p):
                out[sub] = xml_digests(p)
        return out

    ok = str(tmp_path / "ok")
    os.makedirs(ok)
    argv = [DES, FA, "--seed", SEED]
    assert main(argv + ["--output-dir", ok]) == 0
    killed = str(tmp_path / "killed")
    os.makedirs(killed)
    faults.arm("search.round", "raise", "1")
    try:
        with pytest.raises(InjectedFault):
            main(argv + ["--output-dir", killed])
    finally:
        faults.disarm()
    assert main(["--resume-run", killed]) == 0
    assert digests(killed) == digests(ok)


def test_fresh_run_truncates_stale_journal(tmp_path):
    """A NEW run into a directory owns it: the old journal must not leak
    resume state into the fresh search."""
    d = str(tmp_path)
    assert main([FA, "--seed", "5", "--output-dir", d]) == 0
    first = xml_digests(d)
    assert main([FA, "--seed", "5", "--output-dir", d]) == 0
    assert xml_digests(d) == first


def test_resume_run_without_journal_errors(tmp_path, capsys):
    rc = main(["--resume-run", str(tmp_path)])
    assert rc != 0
    assert "journal" in capsys.readouterr().err


def test_resume_run_rejects_incompatible_journal(tmp_path, capsys):
    """Version mismatch or a missing recorded setting is a one-line
    error, not a KeyError traceback."""
    import json

    from sboxgates_tpu.resilience.journal import JOURNAL_NAME

    d = str(tmp_path)
    assert main([FA, "--seed", "5", "--output-dir", d]) == 0
    path = os.path.join(d, JOURNAL_NAME)
    recs = [json.loads(line) for line in open(path)]
    recs[0]["version"] = 999
    with open(path, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in recs)
    capsys.readouterr()
    assert main(["--resume-run", d]) != 0
    assert "version" in capsys.readouterr().err
    from sboxgates_tpu.resilience.journal import JOURNAL_VERSION

    recs[0]["version"] = JOURNAL_VERSION
    del recs[0]["config"]["pipeline_depth"]  # an "older build's" journal
    with open(path, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in recs)
    assert main(["--resume-run", d]) != 0
    assert "incompatible" in capsys.readouterr().err
    # A version-1 journal (the pre-per-job layout) is rejected by version,
    # never half-read: the v2 layout added shard/per-job records the old
    # reader semantics would silently misresume.
    recs[0]["version"] = 1
    recs[0]["config"]["pipeline_depth"] = 2
    with open(path, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in recs)
    assert main(["--resume-run", d]) != 0
    assert "version 1" in capsys.readouterr().err


def test_resume_run_shard_sweep_mismatch_rejected(tmp_path, capsys):
    """--resume-run restores the execution mode from the journal;
    explicitly passing --shard-sweep against a NON-sharded journal is a
    contradiction and fails with a one-line error (the journal decides),
    while a sharded journal resumes without any extra flags."""
    d = str(tmp_path)
    assert main([FA, "--seed", "5", "--output-dir", d]) == 0
    capsys.readouterr()
    rc = main(["--resume-run", d, "--shard-sweep"])
    assert rc != 0
    err = capsys.readouterr().err
    assert "non-sharded" in err
    assert err.strip().count("\n") == 0
    assert "Traceback" not in err


def _shard_digests(root):
    """{box: {filename: sha256}} for every per-box subdirectory."""
    out = {}
    for sub in sorted(os.listdir(root)):
        p = os.path.join(root, sub)
        if os.path.isdir(p) and not sub.startswith(("shard-", "xla_cache")):
            out[sub] = xml_digests(p)
    return out


def test_shard_sweep_one_output_resumes_bit_identical(tmp_path, capsys):
    """A killed --shard-sweep one-output sweep RESUMES (not restarts):
    the per-job journals replay the completed boxes and continue the
    PRNG exactly — final checkpoints bit-identical to the uninterrupted
    sweep.  Single-process here (the process's slice is the whole
    sweep); the 2-process version lives in test_distributed.py."""
    argv = [FA, "--permute-sweep", "--shard-sweep", "-o", "0", "-l",
            "--seed", SEED]
    ok = str(tmp_path / "ok")
    os.makedirs(ok)
    assert main(argv + ["--output-dir", ok]) == 0
    killed = str(tmp_path / "killed")
    os.makedirs(killed)
    # journal.append hits 1..18 are the run_start records (top-level +
    # shard-00 + 16 per-job journals); job_done records start at 19.
    # Kill after 6 of the 16 permutation jobs have journaled.
    faults.arm("journal.append", "raise", "24")
    try:
        with pytest.raises(InjectedFault):
            main(argv + ["--output-dir", killed])
    finally:
        faults.disarm()
    interrupted = _shard_digests(killed)
    assert interrupted != _shard_digests(ok)  # stopped short
    capsys.readouterr()
    assert main(["--resume-run", killed]) == 0
    out = capsys.readouterr().out
    # Resumed, not restarted: the journaled prefix replays from its
    # checkpoints instead of re-searching.
    assert "resumed from the journal" in out or "resumed" in out
    assert _shard_digests(killed) == _shard_digests(ok)
    # The shard run journal lives under shard-00/ (this process is the
    # slice's coordinator).
    assert os.path.exists(
        os.path.join(killed, "shard-00", "search.journal.jsonl")
    )
    # Resuming the now-complete run is a cheap replay that exits 0.
    assert main(["--resume-run", killed]) == 0
    assert _shard_digests(killed) == _shard_digests(ok)


def test_shard_sweep_all_outputs_resumes_bit_identical(tmp_path):
    """The all-outputs (beam) driver under --shard-sweep journals its
    lockstep rounds in the shard journal and resumes bit-identically
    after a mid-round kill."""
    argv = [FA, FA, "--shard-sweep", "--seed", SEED]
    ok = str(tmp_path / "ok")
    os.makedirs(ok)
    assert main(argv + ["--output-dir", ok]) == 0
    killed = str(tmp_path / "killed")
    os.makedirs(killed)
    faults.arm("search.round", "raise", "1")
    try:
        with pytest.raises(InjectedFault):
            main(argv + ["--output-dir", killed])
    finally:
        faults.disarm()
    assert main(["--resume-run", killed]) == 0
    assert _shard_digests(killed) == _shard_digests(ok)


def test_multibox_one_output_resumes_bit_identical(tmp_path, capsys):
    """The (previously journal-free) multibox one-output driver now
    journals per job: killed mid-sweep, it resumes with the completed
    boxes replayed and bit-identical final checkpoints."""
    argv = [DES, FA, "-o", "0", "-i", "2", "-l", "--serial-jobs",
            "--seed", SEED]
    ok = str(tmp_path / "ok")
    os.makedirs(ok)
    assert main(argv + ["--output-dir", ok]) == 0
    killed = str(tmp_path / "killed")
    os.makedirs(killed)
    # Hits 1..3 are run_start records (top-level + 2 job journals);
    # job_done records start at 4.  Kill inside the second box's
    # attempts: the first box must replay, the tail re-run.
    faults.arm("journal.append", "raise", "6")
    try:
        with pytest.raises(InjectedFault):
            main(argv + ["--output-dir", killed])
    finally:
        faults.disarm()
    capsys.readouterr()
    assert main(["--resume-run", killed]) == 0
    assert "resumed" in capsys.readouterr().out
    assert _shard_digests(killed) == _shard_digests(ok)


# -- full matrix: real crashes through the CLI subprocess (slow) ----------


def _run_cli(argv, d, fault=None):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    if fault:
        env["SBG_FAULTS"] = fault
    return subprocess.run(
        [sys.executable, "-m", "sboxgates_tpu", *argv, "--output-dir", d],
        capture_output=True,
        text=True,
        cwd=ROOT,
        env=env,
        timeout=600,
    )


CRASH_MATRIX = [
    ("ckpt.write", "1"),
    ("ckpt.write", "4"),
    ("ckpt.replace", "2"),
    ("journal.append", "2"),
    ("search.round", "1"),
    ("search.round", "3"),
    ("search.node", "3"),
    ("search.node", "9"),
]


@pytest.mark.slow
@pytest.mark.parametrize("site,when", CRASH_MATRIX)
def test_crash_matrix_resumes_bit_identical(tmp_path, site, when):
    """The acceptance property with REAL crashes (os._exit mid-write):
    every site × hit combination resumes to the uninterrupted result."""
    ok = str(tmp_path / "ok")
    os.makedirs(ok)
    argv = [DES, "--seed", SEED]
    r = _run_cli(argv, ok)
    assert r.returncode == 0, r.stderr
    killed = str(tmp_path / "killed")
    os.makedirs(killed)
    r = _run_cli(argv, killed, fault=f"{site}:crash@{when}")
    assert r.returncode == faults.CRASH_EXIT_CODE, (r.stdout, r.stderr)
    r = subprocess.run(
        [sys.executable, "-m", "sboxgates_tpu", "--resume-run", killed],
        capture_output=True,
        text=True,
        cwd=ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=600,
    )
    assert r.returncode == 0, r.stderr
    assert xml_digests(killed) == xml_digests(ok)


@pytest.mark.slow
def test_crash_matrix_lut_mode(tmp_path):
    """LUT-mode search killed mid-run (native-engine path, iteration-
    granular one-output journal) resumes bit-identically too."""
    ok = str(tmp_path / "ok")
    os.makedirs(ok)
    argv = [DES, "-l", "-o", "0", "-i", "2", "--seed", SEED]
    r = _run_cli(argv, ok)
    assert r.returncode == 0, r.stderr
    killed = str(tmp_path / "killed")
    os.makedirs(killed)
    r = _run_cli(argv, killed, fault="search.node:crash@2")
    assert r.returncode == faults.CRASH_EXIT_CODE, (r.stdout, r.stderr)
    r = subprocess.run(
        [sys.executable, "-m", "sboxgates_tpu", "--resume-run", killed],
        capture_output=True,
        text=True,
        cwd=ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=600,
    )
    assert r.returncode == 0, r.stderr
    assert xml_digests(killed) == xml_digests(ok)


# -- fused multi-round chain driver: journal identity + resume -------------


def _chain_problem():
    from planted import build_round_chain

    return build_round_chain(n_rounds=10, gates0=12, seed=7)


def _run_chain(tmp_path, name, n_per, rounds=None, st=None, resume=False):
    from sboxgates_tpu.resilience.journal import SearchJournal
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.rounds import run_round_chain

    if st is None:
        st, rounds = _chain_problem()
    d = os.path.join(str(tmp_path), name)
    if resume:
        journal = SearchJournal.resume(d)
    else:
        journal = SearchJournal.start(d, {"mode": "round_chain", "seed": 5})
    ctx = SearchContext(Options(
        lut_graph=True, randomize=True, seed=5, warmup=False,
        parallel_mux=False,
    ))
    outs = run_round_chain(
        ctx, st, rounds, rounds_per_dispatch=n_per, journal=journal
    )
    return st, outs, d


@pytest.mark.parametrize("n_per", [2, 8])
def test_round_chain_journal_byte_identical_across_n(tmp_path, n_per):
    """Fused chains must journal BYTE-identically to the per-round loop:
    records are per round (never per dispatch window), and the PRNG
    block draw makes the recorded rng positions window-independent."""
    st1, outs1, d1 = _run_chain(tmp_path, "serial", 1)
    st2, outs2, d2 = _run_chain(tmp_path, f"fused{n_per}", n_per)
    assert outs1 == outs2
    assert st1.tables.tobytes() == st2.tables.tobytes()
    j1 = open(os.path.join(d1, "search.journal.jsonl"), "rb").read()
    j2 = open(os.path.join(d2, "search.journal.jsonl"), "rb").read()
    assert j1 == j2


@pytest.mark.parametrize("keep_seq", [1, 4])
def test_round_chain_resumes_bit_identical(tmp_path, keep_seq):
    """A chain killed mid-run resumes from its journal to the identical
    final circuit: replay the recorded rounds, restore the PRNG, and
    continue through the fused driver.  keep_seq=1 is the window where
    the seed block was drawn (and journaled) but NO round completed —
    the resume must restore the post-block-draw PRNG position from the
    chain_seeds record itself."""
    import json

    ref_st, ref_outs, ref_dir = _run_chain(tmp_path, "ref", 8)

    # Simulate the crash: truncate the journal after keep_seq records
    # (run_start + chain_seeds + completed rounds) into a fresh dir.
    recs = [
        json.loads(ln) for ln in open(
            os.path.join(ref_dir, "search.journal.jsonl"), encoding="utf-8"
        )
    ]
    kept = [r for r in recs if r["seq"] <= keep_seq]
    killed = os.path.join(str(tmp_path), "killed")
    os.makedirs(killed)
    with open(
        os.path.join(killed, "search.journal.jsonl"), "w", encoding="utf-8"
    ) as f:
        for r in kept:
            f.write(json.dumps(r, sort_keys=True) + "\n")

    st, rounds = _chain_problem()
    res_st, res_outs, res_dir = _run_chain(
        tmp_path, "killed", 8, rounds=rounds, st=st, resume=True
    )
    assert res_outs == ref_outs
    assert res_st.tables.tobytes() == ref_st.tables.tobytes()
    # The resumed journal's chain records must equal the reference's.
    ref_recs = [r for r in recs if r["type"] == "chain_round"]
    res_recs = [
        json.loads(ln) for ln in open(
            os.path.join(res_dir, "search.journal.jsonl"), encoding="utf-8"
        )
    ]
    res_recs = [r for r in res_recs if r["type"] == "chain_round"]
    assert [
        {k: v for k, v in r.items() if k != "seq"} for r in ref_recs
    ] == [
        {k: v for k, v in r.items() if k != "seq"} for r in res_recs
    ]
