"""Multi-host (multi-process) distributed backend tests.

The real deployment is N processes x M local TPU chips over DCN; here two
CPU processes with 4 virtual devices each form an 8-device global mesh —
exercising jax.distributed initialization, cross-process device_put, the
all-gathered verdicts, and lockstep host control end to end (the analog of
the reference's oversubscribed single-host `mpirun -N 4` CI runs,
.travis.yml:40-48).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_pivot_search_agrees():
    """Both processes of a 2-process run must select the identical planted
    5-LUT decomposition through the sharded pivot path, and it must be a
    correct decomposition."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = REPO
    port = str(_free_port())
    worker = os.path.join(REPO, "tests", "distributed_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), port],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=570)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    results = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("RESULT")]
        assert lines, out
        results.append(lines[0].split()[2:])  # drop "RESULT <pid>"
    assert results[0] == results[1], outs

    # Independently verify the decomposition against the planted target.
    from planted import build_planted_lut5, verify_lut5_result

    st, target, mask = build_planted_lut5()
    fo, fi, a, b, c, d, e = (int(x) for x in results[0])
    assert verify_lut5_result(
        st, target, mask,
        {"func_outer": fo, "func_inner": fi, "gates": (a, b, c, d, e)},
    )
