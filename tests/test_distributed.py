"""Multi-host (multi-process) distributed backend tests.

The real deployment is N processes x M local TPU chips over DCN; here two
CPU processes with 4 virtual devices each form an 8-device global mesh —
exercising jax.distributed initialization, cross-process device_put, the
all-gathered verdicts, and lockstep host control end to end (the analog of
the reference's oversubscribed single-host `mpirun -N 4` CI runs,
.travis.yml:40-48).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.parametrize(
    "gather_rows,het_native",
    [(None, False), ("1", False), (None, True)],
    ids=["default", "gather-overflow", "heterogeneous-native"],
)
def test_two_process_pivot_search_agrees(gather_rows, het_native):
    """Both processes of a 2-process run must select the identical planted
    5-LUT decomposition through the sharded pivot path, and it must be a
    correct decomposition.  The second leg (RESULT2/STREAMCHECK) drives
    the chunked path whose multi-host gather is compacted; with
    SBG_GATHER_ROWS=1 the per-device row budget overflows and the
    full-gather re-drive must restore completeness.  The third leg
    (ENGINE) drives the full engine incl. the node-head routing
    agreement; with het_native the native runtime is disabled on process
    1 only, and the agreement must route BOTH processes identically."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = REPO
    if gather_rows is not None:
        env["SBG_GATHER_ROWS"] = gather_rows
    port = str(_free_port())
    worker = os.path.join(REPO, "tests", "distributed_worker.py")
    procs = []
    for i in range(2):
        penv = dict(env)
        if het_native and i == 1:
            penv["SBG_DISABLE_NATIVE"] = "1"
        procs.append(
            subprocess.Popen(
                [sys.executable, worker, str(i), port],
                env=penv,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = [p.communicate(timeout=570)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    results, results2, engines = [], [], []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        lines2 = [l for l in out.splitlines() if l.startswith("RESULT2 ")]
        eng = [l for l in out.splitlines() if l.startswith("ENGINE ")]
        assert lines and lines2 and eng, out
        assert any(l.startswith("STREAMCHECK ") for l in out.splitlines()), out
        assert any(l.startswith("STREAMCHECK7 ") for l in out.splitlines()), out
        results.append(lines[0].split()[2:])  # drop "RESULT <pid>"
        results2.append(lines2[0].split()[2:])
        engines.append(eng[0].split()[2:])
    assert results[0] == results[1], outs
    assert results2[0] == results2[1], outs
    assert engines[0] == engines[1], outs
    if het_native:
        # The agreement must have routed both processes OFF the native
        # head (process 1 has no native runtime).
        assert "native=False" in " ".join(engines[0]), outs

    # Job-sharded sweep (SWEEP lines): the two processes' permutation
    # slices must be disjoint and cover all 16 permutations.
    slices = []
    for out in outs:
        sw = [l for l in out.splitlines() if l.startswith("SWEEP ")]
        assert sw, out
        slices.append(set(sw[0].split()[2].split(",")))
    assert not (slices[0] & slices[1]), outs
    assert slices[0] | slices[1] == {f"p{p:02x}" for p in range(16)}, outs

    # Independently verify both decompositions against the planted targets.
    from planted import (
        build_planted_lut5,
        build_planted_lut5_small,
        verify_lut5_result,
    )

    for build, res in (
        (build_planted_lut5, results[0]),
        (build_planted_lut5_small, results2[0]),
    ):
        st, target, mask = build()
        fo, fi, a, b, c, d, e = (int(x) for x in res)
        assert verify_lut5_result(
            st, target, mask,
            {"func_outer": fo, "func_inner": fi, "gates": (a, b, c, d, e)},
        )


# -- replicated degradation protocol (2 real processes) --------------------


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["transient", "exhaust"])
def test_two_process_replicated_abort(mode):
    """Acceptance: a rank-targeted ``dispatch.sweep@rank:1`` hang on a
    2-process mesh.  Both ranks agree on the breach at the verdict
    barrier and abandon the collective together — ``transient`` (hang
    once) recovers the device path after one agreed re-issue;
    ``exhaust`` (hang every window) degrades BOTH ranks to the
    host-fallback driver in lockstep, without deadlock, and the final
    circuit is bit-identical to the unfaulted run (asserted inside the
    worker; the parent asserts both ranks report identical lines)."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = REPO
    port = str(_free_port())
    worker = os.path.join(REPO, "tests", "distributed_degrade_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), port, mode],
            env=dict(env),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=540)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    refs, degrades = [], []
    for out in outs:
        ref = [l for l in out.splitlines() if l.startswith("REF ")]
        deg = [l for l in out.splitlines() if l.startswith("DEGRADE ")]
        assert ref and deg, out
        refs.append(ref[0].split()[2:])
        degrades.append(deg[0].split()[2:])
    assert refs[0] == refs[1], outs
    assert degrades[0] == degrades[1], outs


@pytest.mark.slow
def test_two_process_shard_sweep_killed_rank_resumes(tmp_path):
    """Kill-one-rank crash matrix for the journaled shard sweep: rank 1
    of a 2-process ``--shard-sweep --permute-sweep`` run is killed
    mid-slice (``search.round:crash``); ``--resume-run`` with 2 fresh
    processes RESUMES — rank 0's completed shard replays, rank 1
    continues from its per-job journals — and every per-box checkpoint
    is bit-identical to the uninterrupted 2-process sweep."""
    import hashlib

    from sboxgates_tpu.resilience import faults as _faults

    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["SBG_WARMUP"] = "0"
    FA = os.path.join(REPO, "tests", "data", "crypto1_fa.txt")

    def run_pair(outdir, argv_extra, rank1_fault=None, may_fail=()):
        port = str(_free_port())
        procs = []
        for i in range(2):
            penv = dict(env)
            if rank1_fault and i == 1:
                penv["SBG_FAULTS"] = rank1_fault
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-m", "sboxgates_tpu",
                        *argv_extra,
                        "--coordinator", f"127.0.0.1:{port}",
                        "--num-processes", "2", "--process-id", str(i),
                        "--output-dir", outdir,
                    ],
                    env=penv,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                    cwd=REPO,
                )
            )
        outs = []
        for i, p in enumerate(procs):
            try:
                outs.append(p.communicate(timeout=420)[0])
            except subprocess.TimeoutExpired:
                # A rank whose peer was killed may park in the
                # distributed shutdown barrier after its own (durable)
                # work is done; reap it.
                p.kill()
                outs.append(p.communicate()[0])
            if i not in may_fail:
                assert p.returncode == 0, (i, outs)
        return procs, outs

    def digests(root):
        out = {}
        for sub in sorted(os.listdir(root)):
            p = os.path.join(root, sub)
            if os.path.isdir(p) and sub.startswith("p"):
                out[sub] = {
                    f: hashlib.sha256(
                        open(os.path.join(p, f), "rb").read()
                    ).hexdigest()
                    for f in sorted(os.listdir(p))
                    if f.endswith(".xml")
                }
        return out

    argv = [FA, "--permute-sweep", "--shard-sweep", "-o", "0", "-l",
            "--seed", "7"]
    ok = str(tmp_path / "ok")
    os.makedirs(ok)
    run_pair(ok, argv)
    ref = digests(ok)
    assert ref and all(d for d in ref.values()), ref

    killed = str(tmp_path / "killed")
    os.makedirs(killed)
    procs, outs = run_pair(
        killed, argv, rank1_fault="search.round:crash@3",
        may_fail=(0, 1),
    )
    assert procs[1].returncode == _faults.CRASH_EXIT_CODE, outs
    assert digests(killed) != ref  # rank 1 died mid-slice

    resume_argv = ["--resume-run", killed]
    _, outs = run_pair(killed, resume_argv)
    assert any("resumed" in o for o in outs), outs
    assert digests(killed) == ref, outs
