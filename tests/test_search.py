"""End-to-end search tests on real S-boxes."""

import os

import numpy as np
import pytest

from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import GATES, NO_GATE, SAT, State
from sboxgates_tpu.search import (
    Options,
    SearchContext,
    create_circuit,
    generate_graph,
    generate_graph_one_output,
    make_targets,
    sbox_num_outputs,
)
from sboxgates_tpu.utils.sbox import load_sbox

DATA = os.path.join(os.path.dirname(__file__), "data")


def verify_outputs(st, sbox, num_inputs):
    """Every mapped output gate's table must equal the S-box bit on the
    valid positions."""
    mask = tt.mask_table(num_inputs)
    for bit, gid in enumerate(st.outputs):
        if gid == NO_GATE:
            continue
        target = tt.target_table(sbox, bit)
        assert bool(tt.eq_mask(st.table(gid), target, mask)), f"output {bit}"


def run_single_output(path, output, **opt_kwargs):
    sbox, n = load_sbox(path)
    targets = make_targets(sbox)
    opt = Options(seed=42, **opt_kwargs)
    ctx = SearchContext(opt)
    st = State.init_inputs(n)
    results = generate_graph_one_output(
        ctx, st, targets, output, save_dir=None, log=lambda s: None
    )
    return results, sbox, n


def test_identity_sbox_trivial():
    """identity.txt outputs are just the input variables — zero new gates."""
    results, sbox, n = run_single_output(os.path.join(DATA, "identity.txt"), 0)
    assert results
    st = results[-1]
    assert st.num_gates - st.num_inputs == 0
    verify_outputs(st, sbox, n)


def test_crypto1_fa_search():
    """4-input single-output filter function: a real but fast search."""
    results, sbox, n = run_single_output(os.path.join(DATA, "crypto1_fa.txt"), 0)
    assert results, "search failed"
    st = results[-1]
    verify_outputs(st, sbox, n)
    assert st.num_gates - st.num_inputs <= 12


def test_des_s1_bit0_search():
    """DES S1 output bit 0 — the reference's showcase example finds 19
    gates (README.md:33-34); we only require a valid circuit."""
    results, sbox, n = run_single_output(os.path.join(DATA, "des_s1.txt"), 0)
    assert results, "search failed"
    st = results[-1]
    verify_outputs(st, sbox, n)
    assert st.num_gates - st.num_inputs <= 40


def test_des_s1_bit0_sat_metric_with_nots():
    """SAT-metric objective with NOT-augmented functions (the CI config
    mpirun -N 4 ... -i 3 -o 0 -s -n, .travis.yml:40)."""
    results, sbox, n = run_single_output(
        os.path.join(DATA, "des_s1.txt"), 0, metric=SAT, try_nots=True, iterations=2
    )
    assert results
    verify_outputs(results[-1], sbox, n)
    assert results[-1].sat_metric > 0


def test_crypto1_fa_lut_search():
    """LUT-mode search on the 4-input filter function."""
    results, sbox, n = run_single_output(
        os.path.join(DATA, "crypto1_fa.txt"), 0, lut_graph=True
    )
    assert results
    st = results[-1]
    verify_outputs(st, sbox, n)
    # LUT graphs should be very small for a 4-input function
    assert st.num_gates - st.num_inputs <= 4


def test_budget_ratchet():
    """Second iteration must not produce a worse circuit than the first."""
    sbox, n = load_sbox(os.path.join(DATA, "crypto1_fa.txt"))
    targets = make_targets(sbox)
    ctx = SearchContext(Options(seed=7, iterations=3))
    st = State.init_inputs(n)
    results = generate_graph_one_output(
        ctx, st, targets, 0, save_dir=None, log=lambda s: None
    )
    sizes = [r.num_gates for r in results]
    # ratchet: every later success is no bigger than earlier ones
    for a, b in zip(sizes, sizes[1:]):
        assert b <= a


def test_budget_rejects_worse_circuit():
    """The gate budget must actually *reject*: a tighter max_gates either
    fails or yields a circuit within the budget, and an impossible budget
    always returns NO_GATE (reference: add_gate / check_num_gates_possible,
    sboxgates.c:97-128, 270-278)."""
    sbox, n = load_sbox(os.path.join(DATA, "crypto1_fa.txt"))
    targets = make_targets(sbox)
    mask = tt.mask_table(n)
    ctx = SearchContext(Options(seed=7))
    st = State.init_inputs(n)
    results = generate_graph_one_output(
        ctx, st, targets, 0, save_dir=None, log=lambda s: None
    )
    assert results
    best = results[-1].num_gates

    # Budget one below the found size: any success must fit the budget.
    st2 = State.init_inputs(n)
    st2.max_gates = best - 1
    out = create_circuit(SearchContext(Options(seed=7)), st2, targets[0], mask, [])
    assert out == NO_GATE or st2.num_gates <= best - 1

    # Budget that admits no new gates at all: must be rejected outright.
    st3 = State.init_inputs(n)
    st3.max_gates = st3.num_gates
    assert (
        create_circuit(SearchContext(Options(seed=7)), st3, targets[0], mask, [])
        == NO_GATE
    )
    assert st3.num_gates == n  # nothing was appended


def test_non_randomized_runs_are_identical():
    """randomize=False must be deterministic end to end: two runs produce
    byte-identical circuits (the reference's unshuffled scan order; kernels
    select first-in-order via the negative-seed deterministic priority)."""
    sbox, n = load_sbox(os.path.join(DATA, "crypto1_fa.txt"))
    targets = make_targets(sbox)

    def run():
        ctx = SearchContext(Options(randomize=False))
        st = State.init_inputs(n)
        res = generate_graph_one_output(
            ctx, st, targets, 0, save_dir=None, log=lambda s: None
        )
        assert res
        return [(g.type, g.in1, g.in2, g.in3, g.function) for g in res[-1].gates]

    assert run() == run()


def test_planted_7lut_found_via_search():
    """A target planted as LUT(LUT(a,b,c), LUT(d,e,f), g) over the 8 input
    gates must be solved by the LUT search in at most 3 added gates —
    exercising the 7-LUT phase (fused single-chunk path at this size)
    through the real create_circuit driver."""
    from sboxgates_tpu.core import ttable as tt

    st = State.init_inputs(8)
    outer = tt.eval_lut(0x1D, st.table(0), st.table(1), st.table(2))
    middle = tt.eval_lut(0xB2, st.table(3), st.table(4), st.table(5))
    target = tt.eval_lut(0x6A, outer, middle, st.table(6))
    mask = tt.mask_table(8)
    ctx = SearchContext(Options(seed=11, lut_graph=True))
    from sboxgates_tpu.search import create_circuit

    out = create_circuit(ctx, st, target, mask, [])
    assert out != NO_GATE
    assert bool(tt.eq_mask(st.table(out), target, mask))
    assert st.num_gates - st.num_inputs <= 3
    assert ctx.stats["lut7_candidates"] > 0  # the 7-LUT phase actually ran


@pytest.mark.slow
def test_full_graph_linear_sbox():
    """Full multi-output beam search on the 8x8 linear sanity box."""
    sbox, n = load_sbox(os.path.join(DATA, "linear.txt"))
    targets = make_targets(sbox)
    ctx = SearchContext(Options(seed=3))
    st = State.init_inputs(n)
    beam = generate_graph(ctx, st, targets, save_dir=None, log=lambda s: None)
    assert beam
    final = beam[0]
    assert all(o != NO_GATE for o in final.outputs[: sbox_num_outputs(targets)])
    verify_outputs(final, sbox, n)


def test_single_output_oneoutput_range():
    sbox, n = load_sbox(os.path.join(DATA, "crypto1_fa.txt"))
    targets = make_targets(sbox)
    assert sbox_num_outputs(targets) == 1
