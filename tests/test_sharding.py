"""Multi-device sharding tests on the 8-device virtual CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import NO_GATE, State
from sboxgates_tpu.ops import combinatorics as comb
from sboxgates_tpu.ops import sweeps
from sboxgates_tpu.parallel import MeshPlan, lut5_fused_step, make_mesh
from sboxgates_tpu.search import (
    Options,
    SearchContext,
    generate_graph_one_output,
    make_targets,
)
from sboxgates_tpu.utils.sbox import load_sbox

import os

DATA = os.path.join(os.path.dirname(__file__), "data")


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_fused_step_sharded_equals_single():
    """The sharded fused 5-LUT step must produce the same result as the
    unsharded run (priorities are index-hashes, independent of placement)."""
    rng = np.random.default_rng(5)
    tables = tt.from_bits(rng.integers(0, 2, size=(16, 256)).astype(bool))
    outer = tt.eval_lut(0x5B, tables[1], tables[3], tables[5])
    target = tt.eval_lut(0xC9, outer, tables[7], tables[9])
    mask = tt.mask_table(8)
    stream = comb.CombinationStream(16, 5)
    combos = stream.next_chunk(4096)
    combos, nvalid = comb.pad_rows(combos, 4096)
    valid = np.arange(4096) < nvalid
    _, w_tab, m_tab = sweeps.lut5_split_tables()

    args_np = (tables, combos, valid, target, mask, w_tab, m_tab)
    single = lut5_fused_step(*(jnp.asarray(a) for a in args_np), 99)

    plan = MeshPlan(make_mesh())
    sharded = lut5_fused_step(
        plan.replicate(tables),
        plan.shard_chunk(combos),
        plan.shard_chunk(valid),
        plan.replicate(target),
        plan.replicate(mask),
        plan.replicate(w_tab),
        plan.replicate(m_tab),
        99,
    )
    assert bool(single[0]) and bool(sharded[0])
    assert int(single[1]) == int(sharded[1])
    assert int(single[2]) == int(sharded[2])


def test_search_with_mesh_matches_unsharded():
    """A full LUT search through the search stack with a mesh plan returns
    an equivalent (verified) circuit."""
    sbox, n = load_sbox(os.path.join(DATA, "crypto1_fa.txt"))
    targets = make_targets(sbox)

    st1 = State.init_inputs(n)
    ctx1 = SearchContext(Options(seed=11, lut_graph=True))
    r1 = generate_graph_one_output(ctx1, st1, targets, 0, save_dir=None, log=lambda s: None)

    st2 = State.init_inputs(n)
    plan = MeshPlan(make_mesh())
    ctx2 = SearchContext(Options(seed=11, lut_graph=True), mesh_plan=plan)
    r2 = generate_graph_one_output(ctx2, st2, targets, 0, save_dir=None, log=lambda s: None)

    assert r1 and r2
    mask = tt.mask_table(n)
    for res in (r1[-1], r2[-1]):
        gid = res.outputs[0]
        assert gid != NO_GATE
        assert bool(tt.eq_mask(res.table(gid), targets[0], mask))
    # identical seeds + placement-independent priorities => same circuit
    assert r1[-1].num_gates == r2[-1].num_gates


def test_lut5_pivot_sharded_equals_single():
    """The pivot 5-LUT sweep — the production path for large C(G,5) — must
    select the *identical* decomposition on the 8-device mesh as on a single
    device when not randomizing (round-1 VERDICT item 1: the fast path was
    single-chip-only)."""
    from sboxgates_tpu.search.lut import PIVOT_MIN_TOTAL, lut5_search

    from planted import build_planted_lut5, verify_lut5_result

    st, target, mask = build_planted_lut5()
    assert comb.n_choose_k(st.num_gates, 5) >= PIVOT_MIN_TOTAL

    ctx1 = SearchContext(Options(lut_graph=True, randomize=False))
    res1 = lut5_search(ctx1, st, target, mask, [])

    plan = MeshPlan(make_mesh())
    ctx2 = SearchContext(Options(lut_graph=True, randomize=False), mesh_plan=plan)
    res2 = lut5_search(ctx2, st, target, mask, [])

    assert res1 is not None and res2 is not None
    assert res1 == res2
    assert verify_lut5_result(st, target, mask, res1)


def test_lut5_pivot_sharded_backend_levers(monkeypatch, capsys):
    """The sharded stream honors the backend lever: xla_bf16 selects the
    identical decomposition (counts <= 256 are exact in bf16), and a
    pallas setting falls back to the XLA matmul half loudly — a
    per-call stderr line plus a ctx.stats counter, not a warnings.warn
    that Python's default filter dedups to one line per process
    (round-5 review finding + ADVICE round 5)."""
    from planted import build_planted_lut5

    from sboxgates_tpu.parallel import mesh as pmesh
    from sboxgates_tpu.search.lut import lut5_search

    st, target, mask = build_planted_lut5()
    plan = MeshPlan(make_mesh())

    def run():
        ctx = SearchContext(
            Options(lut_graph=True, randomize=False), mesh_plan=plan
        )
        return lut5_search(ctx, st, target, mask, []), ctx

    base, bctx = run()
    assert base is not None
    assert bctx.stats["pivot_pallas_fallbacks"] == 0
    monkeypatch.setenv("SBG_PIVOT_BACKEND", "xla_bf16")
    assert run()[0] == base
    monkeypatch.setenv("SBG_PIVOT_BACKEND", "pallas")
    # The stderr line is rate-limited by the process-global counter:
    # reset it so the assertion is independent of test order / reruns.
    monkeypatch.setattr(pmesh, "_PALLAS_FALLBACKS", 0)
    capsys.readouterr()
    res, ctx = run()
    assert res == base
    assert ctx.stats["pivot_pallas_fallbacks"] >= 1
    assert pmesh.pallas_fallback_count() >= 1
    assert "single-device-only" in capsys.readouterr().err


def test_engine_continuation_under_mesh_matches_unmeshed():
    """Under a local 8-device mesh the native engine drives pivot-sized
    LUT nodes too (uses_native_engine: no rendezvous under a mesh), with
    the continuation service dispatching the SHARDED pivot stream.  The
    full create_circuit result must equal the unmeshed engine run's, the
    engine must stay active (no Python nodes), and the service must have
    been exercised."""
    from planted import build_planted_lut5

    from sboxgates_tpu.search.kwan import create_circuit

    results = {}
    for plan in (None, MeshPlan(make_mesh())):
        st, target, mask = build_planted_lut5()
        ctx = SearchContext(
            Options(seed=3, lut_graph=True, randomize=False),
            mesh_plan=plan,
        )
        out = create_circuit(ctx, st, target, mask, [])
        assert out != NO_GATE
        st.verify_gate(out, target, mask)
        assert ctx.stats["engine_devcalls"] >= 1
        assert ctx.stats.get("python_nodes", 0) == 0
        results[plan is None] = (
            out, [(g.type, g.in1, g.in2, g.in3, g.function) for g in st.gates]
        )
    assert results[True] == results[False]


def test_restart_batched_filter():
    from sboxgates_tpu.parallel.mesh import restart_batched_filter

    rng = np.random.default_rng(2)
    tables = tt.from_bits(rng.integers(0, 2, size=(12, 256)).astype(bool))
    targets = tt.from_bits(rng.integers(0, 2, size=(4, 256)).astype(bool))
    mask = tt.mask_table(8)
    stream = comb.CombinationStream(12, 5)
    combos = stream.next_chunk(512)
    combos, nvalid = comb.pad_rows(combos, 512)
    valid = np.arange(512) < nvalid
    batched = restart_batched_filter()
    feas, r1, r0 = batched(
        jnp.asarray(tables),
        jnp.asarray(combos),
        jnp.asarray(valid),
        jnp.asarray(targets),
        jnp.asarray(mask),
    )
    assert feas.shape == (4, 512)
    for i in range(4):
        f1, _, _ = sweeps.lut_filter(
            jnp.asarray(tables),
            jnp.asarray(combos),
            jnp.asarray(valid),
            jnp.asarray(targets[i]),
            jnp.asarray(mask),
        )
        assert np.array_equal(np.asarray(feas[i]), np.asarray(f1))


def test_graft_entry():
    """entry() compiles and runs; dryrun_multichip(8) completes."""
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    import os as _os

    cwd = _os.getcwd()
    _os.chdir("/root/repo")
    try:
        ge.dryrun_multichip(8)
    finally:
        _os.chdir(cwd)


def test_lut5_host_fallback_matches_device_stream():
    """The host-chunked 5-LUT fallback (used beyond int32 rank space) finds
    a verified decomposition equivalent to the device stream's."""
    from sboxgates_tpu.search.lut import _lut5_search_host, lut5_search

    rng = np.random.default_rng(5)
    st = State.init_inputs(8)
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.graph.state import GATES

    while st.num_gates < 14:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    outer = tt.eval_lut(0x2D, st.table(2), st.table(6), st.table(11))
    target = tt.eval_lut(0xB4, outer, st.table(4), st.table(9))
    mask = tt.mask_table(8)

    for fn in (lut5_search, _lut5_search_host):
        ctx = SearchContext(Options(seed=13, lut_graph=True))
        res = fn(ctx, st, target, mask, [])
        assert res is not None, fn.__name__
        a, b, c, d, e = res["gates"]
        got = tt.eval_lut(
            res["func_inner"],
            tt.eval_lut(res["func_outer"], st.table(a), st.table(b), st.table(c)),
            st.table(d),
            st.table(e),
        )
        assert bool(tt.eq_mask(got, target, mask)), fn.__name__


def test_shard_chunk_pads_to_multiple():
    plan = MeshPlan(make_mesh())  # 8 virtual devices
    arr = np.arange(10, dtype=np.uint32)  # 10 % 8 != 0
    out = plan.shard_chunk(arr, fill=0xFFFFFFFF)
    assert out.shape[0] == 16
    got = np.asarray(out)
    assert np.array_equal(got[:10], arr)
    assert (got[10:] == 0xFFFFFFFF).all()


def test_lut7_capped_overflow_sharded():
    """An overflowed 7-LUT search end to end on the mesh (VERDICT r2 item
    5): stage A collects ~1.5k feasible tuples — past the fused-head
    single-chunk limit AND every host-solve threshold — so stage B runs
    the sharded pair-matmul device solver over the hit list.  The found
    decomposition must verify, and both stages must have seen the
    overflow row counts."""
    from planted import build_planted_lut7, verify_lut7_result

    from sboxgates_tpu.search.context import (
        LUT7_HEAD_SOLVE_ROWS,
        NATIVE_LUT7_SOLVE_MAX,
    )
    from sboxgates_tpu.search.lut import lut7_search

    st, target, mask = build_planted_lut7()
    ctx = SearchContext(
        Options(seed=1, lut_graph=True, randomize=False),
        mesh_plan=MeshPlan(make_mesh()),
    )
    res = lut7_search(ctx, st, target, mask, [])
    assert res is not None, "capped 7-LUT search found nothing"
    assert verify_lut7_result(st, target, mask, res)
    # Overflow actually happened: more solve rows than any non-staged path
    # could have taken.
    assert ctx.stats["lut7_solved"] > max(
        LUT7_HEAD_SOLVE_ROWS, NATIVE_LUT7_SOLVE_MAX
    )
    assert ctx.stats["lut7_candidates"] > 0


def test_pivot_tile_batch_parity(monkeypatch):
    """tile_batch=2 must return the identical decomposition (and a
    genuine miss, exercising the batched exhaustion path) as
    tile_batch=1 — selection is tile-order resolved, so non-randomized
    runs are bit-identical for every batch size."""
    from functools import reduce

    from planted import build_planted_lut5

    from sboxgates_tpu.search.lut import lut5_search

    st, target, mask = build_planted_lut5()
    # AND of all 8 inputs is 1 at exactly one point; the state's gates
    # are all linear (IN/XOR), and any 5 linear forms partition the cube
    # into cells of >= 8 points, so the single-1 cell always mixes
    # required values: infeasible for EVERY tuple — a guaranteed miss.
    miss_target = reduce(
        lambda a, b: np.asarray(a) & np.asarray(b),
        [st.table(i) for i in range(8)],
    )

    def run():
        ctx = SearchContext(Options(seed=2, lut_graph=True, randomize=False))
        hit = lut5_search(ctx, st, target, mask, [])
        miss = lut5_search(ctx, st, miss_target, mask, [])
        return hit, miss

    monkeypatch.setenv("SBG_PIVOT_PIPELINE", "0")
    base_hit, base_miss = run()
    assert base_hit is not None and base_miss is None
    monkeypatch.setenv("SBG_PIVOT_TILE_BATCH", "2")
    b2_hit, b2_miss = run()
    assert base_hit == b2_hit
    assert b2_miss is None
    # The double-buffer lever (SBG_PIVOT_PIPELINE) must be bit-identical
    # too — alone and combined with tile batching.
    monkeypatch.setenv("SBG_PIVOT_PIPELINE", "1")
    pb_hit, pb_miss = run()
    assert base_hit == pb_hit and pb_miss is None
    monkeypatch.setenv("SBG_PIVOT_TILE_BATCH", "1")
    p_hit, p_miss = run()
    assert base_hit == p_hit and p_miss is None
    # The bf16-accumulation backend must be bit-identical too: counts
    # <= 256 are exact in bfloat16, so its > 0 verdicts match the int32
    # path's (sweeps._pivot_tile_from_operands_bf16) — alone and
    # composed with both levers.
    monkeypatch.setenv("SBG_PIVOT_BACKEND", "xla_bf16")
    bf_hit, bf_miss = run()
    assert base_hit == bf_hit and bf_miss is None
    monkeypatch.setenv("SBG_PIVOT_BACKEND", "xla_f8")
    f8_hit, f8_miss = run()
    assert base_hit == f8_hit and f8_miss is None
    monkeypatch.setenv("SBG_PIVOT_BACKEND", "xla_bf16")
    monkeypatch.setenv("SBG_PIVOT_TILE_BATCH", "2")
    bfb_hit, bfb_miss = run()
    assert base_hit == bfb_hit and bfb_miss is None
