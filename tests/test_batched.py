"""Batched-restart driver tests: the --iterations axis as a device batch
(SURVEY.md §2.10; BASELINE configs 4-5)."""

import os

import numpy as np

from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import NO_GATE, SAT, State
from sboxgates_tpu.search import (
    Options,
    SearchContext,
    generate_graph_one_output,
    make_targets,
)
from sboxgates_tpu.utils.sbox import load_sbox

DATA = os.path.join(os.path.dirname(__file__), "data")


def _run(path, r, **kw):
    sbox, n = load_sbox(path)
    targets = make_targets(sbox)
    ctx = SearchContext(Options(seed=9, iterations=r, batch_restarts=True, **kw))
    st = State.init_inputs(n)
    results = generate_graph_one_output(
        ctx, st, targets, 0, save_dir=None, log=lambda s: None
    )
    return ctx, results, sbox, n, targets


def test_batched_restarts_gate_mode():
    """R=4 gate-mode restarts: every returned circuit is valid, the batch
    actually batched (fewer dispatches than submits), and the best-last
    ordering holds.  Forces the device-kernel path — natively-routed
    small states never submit to the rendezvous."""
    ctx, results, sbox, n, targets = _run(
        os.path.join(DATA, "crypto1_fa.txt"), 4, host_small_steps=False
    )
    assert results, "no restart found a circuit"
    mask = tt.mask_table(n)
    for res in results:
        gid = res.outputs[0]
        assert gid != NO_GATE
        assert bool(tt.eq_mask(res.table(gid), targets[0], mask))
    sizes = [r.num_gates for r in results]
    assert sizes == sorted(sizes, reverse=True), "best-last ordering"
    # the rendezvous must have batched: one vmapped dispatch serves many
    # same-kind submits
    assert ctx.stats["restart_batch_submits"] > 0
    assert (
        ctx.stats["restart_batch_dispatches"]
        < ctx.stats["restart_batch_submits"]
    )


def test_batched_restarts_diverse():
    """Different restarts use different PRNG streams, so a batch usually
    returns more than one distinct circuit size/shape; at minimum all are
    valid and stats accumulate."""
    ctx, results, sbox, n, targets = _run(
        os.path.join(DATA, "des_s1.txt"), 3
    )
    assert results
    assert ctx.stats["pair_candidates"] > 0


def test_batched_restarts_sat_metric():
    ctx, results, sbox, n, targets = _run(
        os.path.join(DATA, "crypto1_fa.txt"), 3, metric=SAT, try_nots=True
    )
    assert results
    sats = [r.sat_metric for r in results]
    assert sats == sorted(sats, reverse=True)


def test_batched_full_graph_beam():
    """--batch-iterations applies to the multi-output beam search: each
    round's (iteration x start x output) jobs run as one rendezvous batch."""
    from sboxgates_tpu.search import generate_graph, sbox_num_outputs

    sbox, n = load_sbox(os.path.join(DATA, "identity.txt"))
    targets = make_targets(sbox)
    # device-kernel path forced: native-routed nodes don't submit
    ctx = SearchContext(
        Options(seed=4, iterations=2, batch_restarts=True,
                host_small_steps=False)
    )
    st = State.init_inputs(n)
    beam = generate_graph(ctx, st, targets, save_dir=None, log=lambda s: None)
    assert beam
    final = beam[0]
    assert all(
        o != NO_GATE for o in final.outputs[: sbox_num_outputs(targets)]
    )
    assert ctx.stats["restart_batch_submits"] > 0


def test_batched_error_propagates(monkeypatch):
    """A kernel failure inside a rendezvous flush must raise in the caller,
    not deadlock the other restart threads."""
    import pytest

    from sboxgates_tpu.ops import sweeps as sw
    from sboxgates_tpu.search import batched

    def boom(*a, **k):
        raise RuntimeError("kernel boom")

    monkeypatch.setattr(sw, "gate_step_stream", boom)
    # The process-wide vmap-wrapper cache maps submission keys to real
    # kernels; without a fresh cache a wrapper from an earlier test would
    # bypass the monkeypatched kernel.
    monkeypatch.setattr(batched, "_VMAP_CACHE", {})
    with pytest.raises(RuntimeError, match="kernel boom"):
        # device-kernel path forced so the patched kernel is reached
        _run(os.path.join(DATA, "crypto1_fa.txt"), 3, host_small_steps=False)


def test_batched_workers_joined_when_start_fails(monkeypatch):
    """Regression (jaxlint R15): when a mid-loop ``Thread.start()``
    raises (thread limit), the workers already running must be joined
    before the exception propagates — the caller must never race live
    restart threads over ``results``/``ctx`` stats."""
    import threading
    import time

    import pytest

    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.search import batched
    from sboxgates_tpu.search.batched import run_batched_circuits

    sbox, n = load_sbox(os.path.join(DATA, "crypto1_fa.txt"))
    targets = make_targets(sbox)
    mask = tt.mask_table(n)
    # lut_graph forces the threaded driver: the single-core sequential
    # fast path only covers gate-mode host-only batches.
    ctx = SearchContext(
        Options(seed=9, iterations=2, batch_restarts=True, lut_graph=True)
    )
    st = State.init_inputs(n)
    jobs = [(st.copy(), targets[0], mask) for _ in range(2)]

    first_worker_finished = threading.Event()

    def slow_create(rctx, nst, target, m, gates):
        time.sleep(0.2)
        first_worker_finished.set()
        return NO_GATE

    monkeypatch.setattr(batched, "create_circuit", slow_create)

    real_start = threading.Thread.start
    started = []

    def flaky_start(self):
        if started:
            raise RuntimeError("can't start new thread")
        started.append(self)
        real_start(self)

    monkeypatch.setattr(threading.Thread, "start", flaky_start)
    with pytest.raises(RuntimeError, match="can't start new thread"):
        run_batched_circuits(ctx, jobs)
    # The join ran on the error path: worker 0 completed before the
    # exception escaped, and its thread is gone.
    assert first_worker_finished.is_set()
    assert not started[0].is_alive()
