"""Async double-buffered chunk pipeline (Options.pipeline_depth).

Covers the ISSUE-1 acceptance points: (a) the pipelined host-stream
drivers return bit-identical first hits (rank, gate ids) and identical
candidate statistics vs the serial path, (b) the prefetch queue shuts
down cleanly on an early hit and on a consumer/producer exception, and
(c) pipeline_depth=1 reproduces the historical strictly-serial drivers
(no background thread at all).
"""

import threading
import time

import numpy as np
import pytest

from sboxgates_tpu.ops import combinatorics as comb
from sboxgates_tpu.ops import sweeps
from sboxgates_tpu.search import Options, SearchContext
from sboxgates_tpu.search import lut as slut


def _prefetch_threads():
    return [
        t for t in threading.enumerate()
        if t.name.startswith("sbg-chunk-prefetch")
    ]


def _serial_chunks(g, k, csize, exclude):
    """The historical serial loop's exact chunk sequence."""
    stream = comb.CombinationStream(g, k)
    out = []
    while True:
        chunk = stream.next_chunk(csize)
        if chunk is None:
            return out
        chunk = comb.filter_exclude(chunk, exclude)
        out.append(comb.pad_rows(chunk, csize))


# -- ChunkPrefetcher unit tests -------------------------------------------


@pytest.mark.parametrize("depth", [1, 2, 4])
def test_prefetcher_matches_serial_chunks(depth):
    g, k, csize, excl = 14, 5, 256, [3, 7]
    expect = _serial_chunks(g, k, csize, excl)
    got = []
    with comb.ChunkPrefetcher(
        comb.CombinationStream(g, k), csize, excl, depth=depth
    ) as pf:
        while True:
            item = pf.get()
            if item is None:
                break
            got.append(item)
        # Exhausted streams keep returning None (drivers may over-poll).
        assert pf.get() is None
    assert len(got) == len(expect)
    for (pa, na), (pb, nb) in zip(got, expect):
        assert na == nb
        np.testing.assert_array_equal(pa, pb)


def test_prefetcher_depth1_is_inline():
    """pipeline_depth=1 must reproduce the serial driver exactly: no
    producer thread is ever spawned."""
    before = _prefetch_threads()
    pf = comb.ChunkPrefetcher(comb.CombinationStream(12, 5), 128, (), depth=1)
    assert pf.get() is not None
    assert _prefetch_threads() == before
    assert pf.closed
    pf.close()
    assert pf.closed


def test_prefetcher_early_close_joins_thread():
    """Early hit: the consumer stops reading mid-stream; close() must
    promptly unblock a producer stuck on the bounded queue and join it
    (and stay idempotent)."""
    pf = comb.ChunkPrefetcher(
        comb.CombinationStream(30, 5), 64, (), depth=2
    )
    assert pf.get() is not None  # stream far from exhausted
    pf.close()
    assert pf.closed
    assert pf.get() is None  # closed prefetcher yields nothing
    pf.close()  # idempotent
    assert not _prefetch_threads()


def test_prefetcher_producer_exception_propagates():
    class Boom(RuntimeError):
        pass

    class FailingStream:
        def __init__(self):
            self.inner = comb.CombinationStream(20, 5)
            self.calls = 0

        def next_chunk(self, n):
            self.calls += 1
            if self.calls > 2:
                raise Boom("producer died")
            return self.inner.next_chunk(n)

    pf = comb.ChunkPrefetcher(FailingStream(), 128, (), depth=2)
    got = 0
    with pytest.raises(Boom):
        while True:
            if pf.get() is None:
                break
            got += 1
    assert got == 2  # the chunks produced before the failure arrived intact
    assert pf.get() is None  # the failure ends the stream
    pf.close()
    assert not _prefetch_threads()


def test_prefetcher_consumer_exception_cleans_up():
    """A consumer error inside the with-block must still join the worker
    (the driver loops wrap the prefetcher in a context manager)."""
    with pytest.raises(ValueError):
        with comb.ChunkPrefetcher(
            comb.CombinationStream(30, 5), 64, (), depth=3
        ) as pf:
            assert pf.get() is not None
            raise ValueError("consumer died")
    assert not _prefetch_threads()


# -- Driver determinism on planted instances ------------------------------


def _force_host_path(monkeypatch, chunk5=1024, chunk7=8192):
    """Route lut5/lut7 searches through the host-chunked fallbacks with
    small chunks so the planted spaces span many chunks.  SBG_DEVICE_ENUM=0
    pins the ChunkPrefetcher route: these tests exercise the host chunk
    pipeline itself, which healthy backends otherwise skip in favor of
    the device-resident 64-bit enumeration."""
    monkeypatch.setattr(sweeps, "device_rank_limit", lambda g, k: False)
    monkeypatch.setenv("SBG_DEVICE_ENUM", "0")
    monkeypatch.setattr(slut, "LUT5_CHUNK", chunk5)
    monkeypatch.setattr(slut, "LUT7_CHUNK", chunk7)


def _run_lut5(depth):
    from planted import build_planted_lut5_small

    st, target, mask = build_planted_lut5_small()
    ctx = SearchContext(Options(seed=7, pipeline_depth=depth))
    res = slut.lut5_search(ctx, st, target, mask, [])
    return res, ctx


def test_lut5_host_pipelined_identical_hit(monkeypatch):
    from planted import build_planted_lut5_small, verify_lut5_result

    _force_host_path(monkeypatch)
    (serial, sctx) = _run_lut5(1)
    assert serial is not None
    st, target, mask = build_planted_lut5_small()
    assert verify_lut5_result(st, target, mask, serial)
    for depth in (2, 4):
        piped, pctx = _run_lut5(depth)
        assert piped is not None
        # Bit-identical first hit: same decomposition, same gate ids.
        assert tuple(piped["gates"]) == tuple(serial["gates"])
        assert piped["func_outer"] == serial["func_outer"]
        assert piped["func_inner"] == serial["func_inner"]
        # Identical candidate accounting: in-flight chunks issued after
        # the hit are discarded uncounted.
        assert (
            pctx.stats["lut5_candidates"] == sctx.stats["lut5_candidates"]
        )
        # Early hit mid-stream: the prefetcher thread must be gone.
        assert not _prefetch_threads()


def test_lut5_host_no_hit_exhausts_identically(monkeypatch):
    """No-hit sweeps must examine the identical candidate set."""
    from planted import build_planted_lut5_small

    _force_host_path(monkeypatch)
    st, _, mask = build_planted_lut5_small()
    rng = np.random.default_rng(99)
    # A random target is (overwhelmingly) not a 5-LUT of this state.
    target = rng.integers(0, 2**32, size=8, dtype=np.uint32)
    stats = []
    for depth in (1, 3):
        ctx = SearchContext(Options(seed=7, pipeline_depth=depth))
        assert slut.lut5_search(ctx, st, target, mask, []) is None
        stats.append(ctx.stats["lut5_candidates"])
        assert not _prefetch_threads()
    assert stats[0] == stats[1] > 0


@pytest.mark.skipif(
    not comb._THREAD_CHECKS, reason="thread-contract asserts disabled"
)
def test_prefetcher_rejects_second_consumer_thread():
    """Debug-mode enforcement of the thread-safety contract: get() is
    single-consumer; a second reading thread trips the owner assertion
    instead of silently corrupting chunk order."""
    stream = comb.CombinationStream(10, 3)
    with comb.ChunkPrefetcher(stream, chunk_size=8, depth=2) as pf:
        assert pf.get() is not None  # main thread becomes the consumer
        caught = []

        def rogue():
            try:
                pf.get()
            except AssertionError as e:
                caught.append(e)

        t = threading.Thread(target=rogue)
        t.start()
        t.join(timeout=10)
        assert caught and "single-consumer" in str(caught[0])


def test_streaming_sweep_runs_clean_under_runtime_guards(monkeypatch):
    """jaxlint's runtime complement over the real pipelined driver: after
    a warmup sweep the steady state must not recompile (a per-call-varying
    static arg would), and its host-device syncs stay bounded by the
    deliberate per-chunk verdict count — so a regression that adds hidden
    per-chunk transfers fails loudly here, not silently on hardware."""
    import math

    from sboxgates_tpu.utils import recompile_guard, sync_guard

    _force_host_path(monkeypatch)
    _run_lut5(2)  # warmup: all kernel shapes compile here
    with recompile_guard(allowed=0, label="lut5 host stream"), \
            sync_guard(action="count", label="lut5 host stream") as rep:
        res, ctx = _run_lut5(2)
    assert res is not None
    # Syncs scale with chunks, not candidates: the stream resolves a
    # compact verdict (plus at most a hit-row gather and a solve verdict)
    # per chunk — a few sync points each, never per-candidate.
    nchunks = math.ceil(comb.n_choose_k(24, 5) / slut.LUT5_CHUNK) + 2
    assert 0 < rep.syncs <= 6 * nchunks, rep.events[:10]


def test_lut7_host_collect_identical_hits(monkeypatch):
    from planted import build_planted_lut7

    _force_host_path(monkeypatch)
    # A small cap exercises the discard-past-cap semantics: the planted
    # instance has ~1.5k feasible tuples, far beyond 64.
    monkeypatch.setattr(slut, "LUT7_CAP", 64)
    st, target, mask = build_planted_lut7()
    results = []
    for depth in (1, 3):
        ctx = SearchContext(Options(seed=7, pipeline_depth=depth))
        combos, r1, r0 = slut._lut7_collect_hits(ctx, st, target, mask, [])
        results.append((combos, r1, r0, ctx.stats["lut7_candidates"]))
        assert not _prefetch_threads()
    (ca, r1a, r0a, na), (cb, r1b, r0b, nb) = results
    assert len(ca) > 0
    np.testing.assert_array_equal(ca, cb)
    np.testing.assert_array_equal(r1a, r1b)
    np.testing.assert_array_equal(r0a, r0b)
    assert na == nb


def test_host_driver_consumer_error_joins_prefetcher(monkeypatch):
    """lut_filter blowing up mid-sweep must not leak the producer."""
    from planted import build_planted_lut5_small

    _force_host_path(monkeypatch)

    def boom(*a, **k):
        raise RuntimeError("filter died")

    monkeypatch.setattr(sweeps, "lut_filter", boom)
    st, target, mask = build_planted_lut5_small()
    ctx = SearchContext(Options(seed=7, pipeline_depth=3))
    with pytest.raises(RuntimeError, match="filter died"):
        slut.lut5_search(ctx, st, target, mask, [])
    assert not _prefetch_threads()


# -- Overlap accounting ----------------------------------------------------


def test_profiler_overlap_accounting(monkeypatch):
    from planted import build_planted_lut5_small

    _force_host_path(monkeypatch)
    st, _, mask = build_planted_lut5_small()
    rng = np.random.default_rng(99)
    target = rng.integers(0, 2**32, size=8, dtype=np.uint32)
    # Deterministic producer-ahead: on a loaded CI box a starved
    # producer can end up producing every chunk while the consumer sits
    # blocked in get() — the produce spans then nest inside stall spans
    # and off_critical_path_s legitimately reads ~0.  So between get()
    # calls the consumer explicitly waits (outside any stall span) until
    # the prefetch queue is full — guaranteeing chunks get produced off
    # its critical path no matter how the threads are scheduled.
    captured = {}
    real_prefetcher = SearchContext.host_prefetcher

    def capture_prefetcher(self, stream, chunk_size, exclude, phase):
        pf = real_prefetcher(self, stream, chunk_size, exclude, phase)
        captured["pf"] = pf
        return pf

    monkeypatch.setattr(SearchContext, "host_prefetcher", capture_prefetcher)
    real_filter = sweeps.lut_filter

    def queue_full_filter(*a, **k):
        pf = captured.get("pf")
        if pf is not None and not pf._inline:
            deadline = time.perf_counter() + 10.0
            while (
                not pf._q.full() and pf._thread.is_alive()
                and time.perf_counter() < deadline
            ):
                time.sleep(0.001)
        return real_filter(*a, **k)

    monkeypatch.setattr(sweeps, "lut_filter", queue_full_filter)
    ctx = SearchContext(Options(seed=7, pipeline_depth=2))
    assert slut.lut5_search(ctx, st, target, mask, []) is None
    ov = ctx.prof.overlap()
    assert "lut5.host_stream" in ov
    row = ov["lut5.host_stream"]
    assert row["host_produce_s"] > 0.0
    assert row["device_wait_s"] >= 0.0
    # hidden_s is a measured intersection, so it can never exceed
    # either side.
    assert (
        0.0 <= row["hidden_s"]
        <= min(row["host_produce_s"], row["device_wait_s"]) + 1e-9
    )
    # Pipelined: the producer runs ahead, so most production time stays
    # off the consumer's critical path.
    assert row["off_critical_path_s"] > 0.0
    # The overlap rows render in the -vv report.
    assert "pipeline overlap" in ctx.prof.report(ctx.stats)
    # Serial driver: production is inline inside get() — every produce
    # span is also a stall span, so nothing reads as hidden or off the
    # critical path.
    sctx = SearchContext(Options(seed=7, pipeline_depth=1))
    assert slut.lut5_search(sctx, st, target, mask, []) is None
    srow = sctx.prof.overlap()["lut5.host_stream"]
    assert srow["host_produce_s"] > 0.0
    assert srow["consumer_stall_s"] >= srow["host_produce_s"]
    assert srow["hidden_s"] == 0.0
    assert srow["off_critical_path_s"] == 0.0


def test_overlap_interval_intersection():
    """The intersection is measured, not bounded: disjoint produce/wait
    spans hide nothing even when both totals are large."""
    from sboxgates_tpu.utils.profile import PhaseProfiler

    prof = PhaseProfiler()
    prof.add_wait("p", 0.0, 1.0)
    prof.add_produce("p", 2.0, 3.0)  # disjoint
    assert prof.overlap()["p"]["hidden_s"] == 0.0
    prof.add_produce("p", 0.25, 0.75)  # nested in the wait
    row = prof.overlap()["p"]
    assert row["hidden_s"] == pytest.approx(0.5)
    assert row["host_produce_s"] == pytest.approx(1.5)
    # Overlapping produce spans are merged before intersecting.
    prof.add_produce("p", 0.5, 0.9)
    assert prof.overlap()["p"]["hidden_s"] == pytest.approx(0.65)
    # off_critical_path = merged produce time that did NOT elapse inside
    # a consumer stall — an interval measurement, so a disjoint stall
    # (however long) eats nothing...
    prof.add_stall("p", 5.0, 6.0)
    row = prof.overlap()["p"]
    assert row["consumer_stall_s"] == pytest.approx(1.0)
    # merged produce: (0.25, 0.9) + (2, 3) = 1.65 s, none of it stalled.
    assert row["off_critical_path_s"] == pytest.approx(1.65)
    # ...a stall covering the (2, 3) produce span eats exactly it...
    prof.add_stall("p", 1.5, 3.5)
    assert prof.overlap()["p"]["off_critical_path_s"] == pytest.approx(0.65)
    # ...and a stall blanket over every produce span zeroes the metric.
    prof.add_stall("p", 0.0, 16.0)
    assert prof.overlap()["p"]["off_critical_path_s"] == 0.0


def test_overlap_folding_bounded_and_exact(monkeypatch):
    """Long runs must not hold one interval tuple per chunk forever:
    settled intervals fold into scalar accumulators, and folding must
    not change any overlap number (each produce span is folded exactly
    once, so summed per-fold intersections are exact)."""
    from sboxgates_tpu.utils.profile import PhaseProfiler, _OverlapStream

    monkeypatch.setattr(_OverlapStream, "FOLD_AT", 8)
    n = 500
    prof = PhaseProfiler()
    # Pipelined-shaped pattern: produce (i, i+0.5) overlaps wait
    # (i+0.25, i+0.75) by 0.25 and is disjoint from stall (i+0.8, i+0.9).
    for i in range(n):
        prof.add_produce("p", i, i + 0.5)
        prof.add_wait("p", i + 0.25, i + 0.75)
        prof.add_stall("p", i + 0.8, i + 0.9)
    row = prof.overlap()["p"]
    assert row["host_produce_s"] == pytest.approx(0.5 * n)
    assert row["device_wait_s"] == pytest.approx(0.5 * n)
    assert row["consumer_stall_s"] == pytest.approx(0.1 * n)
    assert row["hidden_s"] == pytest.approx(0.25 * n)
    assert row["off_critical_path_s"] == pytest.approx(0.5 * n)
    stream = prof._overlap[("p", threading.get_ident())]
    assert stream.pending_size() <= 3 * _OverlapStream.FOLD_AT
    # Serial-shaped pattern: produce nested in stall — the exact-zero
    # property must survive folding too.
    sprof = PhaseProfiler()
    for i in range(n):
        sprof.add_stall("s", i, i + 0.6)
        sprof.add_produce("s", i + 0.1, i + 0.5)
        sprof.add_wait("s", i + 0.7, i + 0.9)
    srow = sprof.overlap()["s"]
    assert srow["off_critical_path_s"] == 0.0
    assert srow["hidden_s"] == 0.0
    # Producer-less pattern (device-stream drivers record only waits):
    # the pending list is shed, the total is kept.
    wprof = PhaseProfiler()
    for i in range(n):
        wprof.add_wait("w", i, i + 0.5)
    assert wprof.overlap()["w"]["device_wait_s"] == pytest.approx(0.5 * n)
    wstream = wprof._overlap[("w", threading.get_ident())]
    assert wstream.pending_size() <= 3 * _OverlapStream.FOLD_AT


def test_overlap_streams_keyed_per_consumer():
    """Concurrent drivers sharing a phase name must not cross-pollinate:
    consumer A's produce span inside consumer B's device wait is NOT
    hidden work (it saved B nothing)."""
    from sboxgates_tpu.utils.profile import PhaseProfiler

    prof = PhaseProfiler()
    # Consumer A: strictly serial (produce inside its own stall).
    prof.add_stall("p", 0.0, 1.0, consumer=1)
    prof.add_produce("p", 0.2, 0.8, consumer=1)
    # Consumer B: waiting on the device over that same wall-clock span.
    prof.add_wait("p", 0.0, 1.0, consumer=2)
    row = prof.overlap()["p"]
    # One phase row, summed over consumers — but A's produce does not
    # intersect B's wait, and A's own stall keeps it on-critical-path.
    assert row["host_produce_s"] == pytest.approx(0.6)
    assert row["device_wait_s"] == pytest.approx(1.0)
    assert row["hidden_s"] == 0.0
    assert row["off_critical_path_s"] == 0.0


def test_cli_rejects_bad_pipeline_depth():
    from sboxgates_tpu.cli import main

    assert main(["--pipeline-depth", "0"]) != 0


# -- close() hardening ------------------------------------------------------


def test_prefetcher_close_is_idempotent():
    """A second close (consumer __exit__ after a supervising thread
    already closed) must be a no-op, with the worker joined once."""
    pf = comb.ChunkPrefetcher(comb.CombinationStream(30, 5), 64, (), depth=3)
    assert pf.get() is not None
    pf.close()
    assert pf.closed
    pf.close()
    pf.close()
    assert pf.closed
    assert pf.get() is None
    assert not _prefetch_threads()


def test_prefetcher_close_wakes_blocked_consumer():
    """close() from a supervising thread must wake a consumer blocked in
    get() — the drain alone would leave it hanging on the emptied queue
    forever (the pre-hardening bug shape)."""

    class SlowStream:
        """First chunk arrives, then production blocks until released."""

        def __init__(self):
            self.release = threading.Event()
            self.inner = comb.CombinationStream(30, 5)
            self.calls = 0

        def next_chunk(self, n):
            self.calls += 1
            if self.calls > 1:
                self.release.wait(timeout=20.0)
                return None
            return self.inner.next_chunk(n)

    stream = SlowStream()
    pf = comb.ChunkPrefetcher(stream, 64, (), depth=2)
    got = []
    done = threading.Event()

    def consume():
        got.append(pf.get())
        got.append(pf.get())  # blocks: producer is stuck in next_chunk
        done.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.2)
    pf.close()
    stream.release.set()
    assert done.wait(timeout=10.0), "consumer stayed blocked after close()"
    t.join(timeout=10.0)
    assert got[0] is not None and got[1] is None
    # and the worker does not outlive the failed search
    for _ in range(100):
        if pf.closed:
            break
        time.sleep(0.05)
    assert pf.closed
    assert not _prefetch_threads()


def test_prefetcher_close_drains_late_put():
    """The producer may complete one final _put between close()'s first
    drain and its _stop check; the second drain must drop it so no chunk
    arrays stay pinned in the dead prefetcher's queue."""
    for _ in range(10):  # the race window is timing-dependent; iterate
        pf = comb.ChunkPrefetcher(
            comb.CombinationStream(30, 5), 64, (), depth=2
        )
        assert pf.get() is not None
        pf.close()
        # Whatever survived must be at most the wake-up sentinel.
        items = []
        try:
            while True:
                items.append(pf._q.get_nowait())
        except Exception:
            pass
        assert all(i is None for i in items)
        assert not _prefetch_threads()
