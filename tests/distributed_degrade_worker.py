"""One process of the 2-process replicated-degradation tests.

Spawned by test_distributed.py: connects into a 2-process CPU runtime,
runs the planted 5-LUT search over the global (process-spanning) mesh
once unfaulted (the bit-identity reference), then re-runs it with a
rank-targeted ``dispatch.sweep@rank:1`` hang injected and the replicated
deadline guard armed:

- mode ``transient`` — the hang fires exactly once: both ranks must
  agree on the breach at the verdict barrier, abandon + re-issue the
  collective together, and RECOVER on the device path (no degradation).
- mode ``exhaust`` — the hang fires every window: the retry schedule
  exhausts, every rank raises the agreed DispatchTimeout in the same
  window, trips the circuit breaker, and degrades to the host-fallback
  driver in lockstep.

Either way the faulted result must be bit-identical to the unfaulted
reference, and both processes must print identical DEGRADE lines.

Exits via os._exit(0) after flushing: the exercised failure modes leave
abandoned daemon workers parked on wedged waits by design, and a normal
interpreter exit would hand them to the distributed runtime's shutdown
barrier.

Usage: distributed_degrade_worker.py <process_id> <coordinator_port> <mode>
"""

import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]
mode = sys.argv[3]
assert mode in ("transient", "exhaust"), mode

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), "..", ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("SBG_WARMUP", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from sboxgates_tpu.parallel import distributed as dist  # noqa: E402

dist.initialize(f"127.0.0.1:{port}", 2, pid)
assert jax.process_count() == 2, jax.process_count()

from planted import build_planted_lut5_small  # noqa: E402

from sboxgates_tpu.parallel import MeshPlan, make_mesh  # noqa: E402
from sboxgates_tpu.resilience import faults  # noqa: E402
from sboxgates_tpu.search import Options, SearchContext  # noqa: E402
from sboxgates_tpu.search.lut import lut5_search  # noqa: E402


def encode(res):
    return "%d %d %s" % (
        res["func_outer"],
        res["func_inner"],
        " ".join(str(g) for g in res["gates"]),
    )


st, target, mask = build_planted_lut5_small()

ref_ctx = SearchContext(
    Options(lut_graph=True, randomize=False),
    mesh_plan=MeshPlan(make_mesh()),
)
ref = lut5_search(ref_ctx, st, target, mask, [])
assert ref is not None, "unfaulted reference search found nothing"
print("REF %d %s" % (pid, encode(ref)), flush=True)

# Rank-targeted hang: only rank 1's guarded resolve ever blocks; rank 0
# learns of the breach solely through the verdict barrier.  Budgets are
# generous vs a healthy CPU resolve (the kernels are compiled by the
# reference run above) yet keep the hang windows short.
budget, retries = (30.0, 2) if mode == "transient" else (8.0, 2)
faults.arm("dispatch.sweep@rank:1", "hang", "1" if mode == "transient" else "1+")
ctx = SearchContext(
    Options(lut_graph=True, randomize=False, dispatch_timeout_s=budget),
    mesh_plan=MeshPlan(make_mesh()),
)
ctx.deadline_cfg.retries = retries
ctx.deadline_cfg.backoff_s = 0.1
res = lut5_search(ctx, st, target, mask, [])
faults.disarm()

assert res is not None, "faulted search found nothing"
assert res == ref, (res, ref)  # bit-identical to the unfaulted run
s = ctx.stats
assert s["breach_barriers"] >= 1, s
assert s["replicated_aborts"] >= 1, s
if mode == "exhaust":
    # Lockstep degradation: the schedule exhausted, this rank raised the
    # agreed DispatchTimeout, tripped the breaker, and completed on the
    # host-fallback driver (the mesh was demoted to local execution).
    assert s["degraded_ranks"] == 1, s
    assert ctx.device_degraded
    assert ctx.mesh_plan is None
else:
    # Transient: one agreed abort + re-issue recovered the device path.
    assert s["degraded_ranks"] == 0, s
    assert not ctx.device_degraded
    assert s["dispatch_retries"] >= 1, s

print(
    "DEGRADE %d mode=%s res=%s aborts>=1=%s degraded=%d"
    % (
        pid,
        mode,
        encode(res),
        s["replicated_aborts"] >= 1,
        s["degraded_ranks"],
    ),
    flush=True,
)
sys.stdout.flush()
sys.stderr.flush()
# Final rendezvous BEFORE the hard exit: rank 0 hosts the coordination
# service, and exiting while the peer is still inside a barrier/KV wait
# aborts the peer mid-assertion.
client = dist._coordination_client()
if client is not None:
    client.wait_at_barrier("sbg-degrade-done", 120_000)
os._exit(0)
