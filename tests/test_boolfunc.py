"""Tests for the boolean-function algebra layer."""

import numpy as np

from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.core import ttable as tt


def test_default_available_gates():
    funs = bf.create_avail_gates(bf.DEFAULT_AVAILABLE)
    assert [f.fun for f in funs] == [bf.AND, bf.XOR, bf.OR]
    assert all(f.ab_commutative for f in funs)


def test_commutativity_flags():
    for fun in range(16):
        f = bf.create_2_input_fun(fun)
        expected = all(
            bf.get_val(fun, a, b) == bf.get_val(fun, b, a)
            for a in (0, 1)
            for b in (0, 1)
        )
        assert f.ab_commutative == expected, f"fun={fun}"


def test_get_not_functions():
    funs = bf.create_avail_gates(bf.DEFAULT_AVAILABLE)  # AND, XOR, OR
    nots = bf.get_not_functions(funs)
    got = {f.fun for f in nots}
    assert got == {bf.NAND, bf.XNOR, bf.NOR}
    assert all(f.not_out for f in nots)


def test_get_not_functions_skips_existing():
    funs = [bf.create_2_input_fun(bf.AND), bf.create_2_input_fun(bf.NAND)]
    assert bf.get_not_functions(funs) == []


def _brute_force_fun3(avail, try_nots):
    """Oracle: enumerate all fun2(fun1(±A, ±B), ±C) (± out) truth tables."""
    found = set()
    polarities = range(8) if try_nots else (0,)
    for nots in polarities:
        for f1 in avail:
            for f2 in avail:
                fun = 0
                for k in range(8):
                    a, b, c = (k >> 2) & 1, (k >> 1) & 1, k & 1
                    if nots & 4:
                        a ^= 1
                    if nots & 2:
                        b ^= 1
                    if nots & 1:
                        c ^= 1
                    fun |= bf.get_val(f2, bf.get_val(f1, a, b), c) << k
                found.add(fun)
                if try_nots:
                    found.add(~fun & 0xFF)
    return found


def test_fun3_list_matches_brute_force():
    avail = [bf.AND, bf.XOR, bf.OR]
    funs = bf.create_avail_gates(bf.DEFAULT_AVAILABLE)
    for try_nots in (False, True):
        got = bf.get_3_input_function_list(funs, try_nots)
        expected = _brute_force_fun3(avail, try_nots)
        assert {f.fun for f in got} == expected
        # no duplicates
        assert len({f.fun for f in got}) == len(got)


def test_fun3_decompositions_are_valid():
    """Each BoolFunc's recorded decomposition reproduces its truth table."""
    funs = bf.create_avail_gates(bf.DEFAULT_AVAILABLE)
    for f in bf.get_3_input_function_list(funs, True):
        fun = 0
        for k in range(8):
            a, b, c = (k >> 2) & 1, (k >> 1) & 1, k & 1
            a ^= f.not_a
            b ^= f.not_b
            c ^= f.not_c
            v = bf.get_val(f.fun2, bf.get_val(f.fun1, a, b), c)
            v ^= f.not_out
            fun |= v << k
        assert fun == f.fun


def test_fun3_commutativity_flags():
    funs = bf.create_avail_gates(bf.DEFAULT_AVAILABLE)
    for f in bf.get_3_input_function_list(funs, True):
        def val(a, b, c):
            return bf.fun3_val(f.fun, a, b, c)

        ab = all(val(a, b, c) == val(b, a, c) for a in (0, 1) for b in (0, 1) for c in (0, 1))
        ac = all(val(a, b, c) == val(c, b, a) for a in (0, 1) for b in (0, 1) for c in (0, 1))
        bc = all(val(a, b, c) == val(a, c, b) for a in (0, 1) for b in (0, 1) for c in (0, 1))
        assert (f.ab_commutative, f.ac_commutative, f.bc_commutative) == (ab, ac, bc)


def test_permute_fun3():
    rng = np.random.default_rng(7)
    tables = [tt.input_table(i) for i in range(3)]
    for _ in range(20):
        fun = int(rng.integers(0, 256))
        perm = tuple(rng.permutation(3))
        g = bf.permute_fun3(fun, perm)
        # g(t0, t1, t2) must equal fun applied to permuted tables
        got = tt.eval_lut(g, *tables)
        expected = tt.eval_lut(fun, tables[perm[0]], tables[perm[1]], tables[perm[2]])
        assert np.array_equal(got, expected), (fun, perm)


def test_swap_fun2():
    for fun in range(16):
        g = bf.swap_fun2(fun)
        for a in (0, 1):
            for b in (0, 1):
                assert bf.get_val(g, a, b) == bf.get_val(fun, b, a)
