"""Runtime complements of the static pass: recompile_guard / sync_guard.

The acceptance case: a deliberately-injected per-call static-arg
recompile — the exact bug R1 exists for — is caught at runtime by
recompile_guard.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sboxgates_tpu.utils import (
    RecompileError,
    SyncError,
    recompile_guard,
    sync_guard,
)


def test_recompile_guard_catches_static_arg_churn():
    @jax.jit
    def warm(x):
        return x + 1

    churn = jax.jit(lambda x, n: x * n, static_argnums=1)
    churn(jnp.ones(2), 0)  # first compile is expected, outside the guard
    with pytest.raises(RecompileError, match="static arg"):
        with recompile_guard(fns=[churn], allowed=0):
            for n in range(1, 4):  # every n is a fresh static value
                churn(jnp.ones(2), n)


def test_recompile_guard_clean_steady_state():
    f = jax.jit(lambda x: x * 2)
    f(jnp.ones(3))
    with recompile_guard(fns=[f], allowed=0) as report:
        for _ in range(10):
            f(jnp.ones(3))
    assert report.compiles == 0


def test_recompile_guard_allows_budget():
    g = jax.jit(lambda x, n: x + n, static_argnums=1)
    with recompile_guard(fns=[g], allowed=2):
        g(jnp.ones(2), 100)
        g(jnp.ones(2), 101)


def test_recompile_guard_global_mode_counts_process_compiles():
    with pytest.raises(RecompileError):
        with recompile_guard(allowed=0):
            fresh = jax.jit(lambda x: x - 3.5)
            fresh(jnp.ones(4))


def test_recompile_guard_rejects_plain_callables():
    with pytest.raises(TypeError):
        with recompile_guard(fns=[lambda x: x]):
            pass


def test_sync_guard_raises_on_device_asarray():
    a = jnp.arange(8)
    with pytest.raises(SyncError, match="sync"):
        with sync_guard(allowed=0):
            np.asarray(a)


def test_sync_guard_counts_all_entry_points():
    a = jnp.arange(4)
    with sync_guard(action="count") as report:
        np.asarray(a)
        jax.device_get(a)
        jax.block_until_ready(a)
        np.array(a)
    assert report.syncs == 4
    assert any("device_get" in e for e in report.events)


def test_sync_guard_ignores_host_data():
    with sync_guard(allowed=0) as report:
        np.asarray([1, 2, 3])
        np.array((4, 5))
        jax.block_until_ready(np.ones(3))  # numpy in, no device sync
    assert report.syncs == 0


def test_sync_guard_restores_patches():
    before = (np.asarray, jax.device_get)
    with sync_guard(action="count"):
        assert np.asarray is not before[0]
    assert np.asarray is before[0]
    assert jax.device_get is before[1]


def test_sync_guard_allowed_budget():
    a = jnp.arange(3)
    with sync_guard(allowed=1) as report:
        np.asarray(a)  # the one budgeted sync
    assert report.syncs == 1
    with pytest.raises(SyncError):
        with sync_guard(allowed=1):
            np.asarray(a)
            np.asarray(a)
