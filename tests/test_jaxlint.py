"""Tier-1 gate: the shipped tree holds at zero unsuppressed jaxlint
findings.

This self-scan is the regression net the static pass exists for: any PR
that introduces a recompile hazard, a hot-loop sync, a tracer escape, a
lockless thread mutation, or a swallowed exception — without either
fixing it or justifying it inline — fails here.
"""

import json
import os
import subprocess
import sys

from sboxgates_tpu.analysis import lint_paths, load_config

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_package_scan_has_zero_unsuppressed_findings():
    config = load_config(ROOT)
    reports = lint_paths(config=config)
    findings = [f for r in reports for f in r.findings]
    assert findings == [], "\n".join(f.render() for f in findings)
    # sanity: the scan actually covered the package and the inline
    # suppressions are present (each carries a mandatory reason)
    assert len(reports) > 20
    assert sum(len(r.suppressed) for r in reports) > 0


def test_config_comes_from_pyproject():
    config = load_config(ROOT)
    assert config.rules == [
        "R1", "R2", "R3", "R4", "R5", "R6", "R1x", "R2x", "R4x",
    ]
    assert config.whole_program  # cross-module pass is on in the gate
    assert "sboxgates_tpu/search/lut.py" in config.hot_modules
    assert config.is_hot("sboxgates_tpu/ops/sweeps.py")
    assert not config.is_hot("sboxgates_tpu/search/context.py")


def test_committed_baseline_is_zero_findings():
    path = os.path.join(ROOT, "jaxlint_baseline.json")
    with open(path, "r", encoding="utf-8") as f:
        baseline = json.load(f)
    assert baseline["findings"] == []


def test_cli_exits_zero_and_emits_json():
    proc = subprocess.run(
        [sys.executable, "-m", "sboxgates_tpu.analysis", "--format", "json"],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert payload["files_scanned"] > 20


def test_cli_baseline_mode_passes():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "sboxgates_tpu.analysis",
            "--baseline",
            "jaxlint_baseline.json",
        ],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_whole_program_pass_runs_in_gate_and_under_budget(monkeypatch):
    """The shared AST cache keeps the full whole-program scan (per-file
    rules + call graph + R1x/R2x/R4x) inside the CI budget.  The
    structural guard is the real regression net: each module is parsed
    EXACTLY once, however many passes run over it — re-parsing per pass
    is what would blow the wall clock on a big tree."""
    import ast
    import time

    calls = {"n": 0}
    real_parse = ast.parse

    def counting_parse(*args, **kwargs):
        calls["n"] += 1
        return real_parse(*args, **kwargs)

    monkeypatch.setattr(ast, "parse", counting_parse)
    config = load_config(ROOT)
    assert config.whole_program
    t0 = time.monotonic()
    reports = lint_paths(config=config)
    elapsed = time.monotonic() - t0
    assert calls["n"] == len(reports), (
        f"{calls['n']} ast.parse calls for {len(reports)} files — the "
        "whole-program pass must share one parse per module"
    )
    if elapsed >= 5.0:
        # A transient load spike shouldn't flake the gate: retry once
        # and hold the best of the two runs to the budget.
        t0 = time.monotonic()
        lint_paths(config=config)
        elapsed = min(elapsed, time.monotonic() - t0)
    assert elapsed < 5.0, f"whole-program lint took {elapsed:.1f}s"
    # The cross-module pass really ran: the acknowledged-source R2x
    # entries (deliberate compact-verdict syncs) only exist under it.
    sup_rules = {f.rule for r in reports for f in r.suppressed}
    assert "R2x" in sup_rules


def test_whole_program_json_is_deterministic():
    """Two scans of the same tree are byte-identical (sorted traversal
    everywhere — an unsorted dict walk would flake the baseline gate)."""
    outs = []
    for _ in range(2):
        proc = subprocess.run(
            [
                sys.executable, "-m", "sboxgates_tpu.analysis",
                "--format", "json",
            ],
            cwd=ROOT,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        outs.append(proc.stdout)
    assert outs[0] == outs[1]


def test_cli_graph_dump():
    """--graph emits the resolved call graph as deterministic JSON:
    functions, lock/loop-annotated edges, thread and jit roots."""
    outs = []
    for _ in range(2):
        proc = subprocess.run(
            [sys.executable, "-m", "sboxgates_tpu.analysis", "--graph"],
            cwd=ROOT,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        outs.append(proc.stdout)
    assert outs[0] == outs[1]
    graph = json.loads(outs[0])
    assert (
        "sboxgates_tpu.ops.combinatorics:ChunkPrefetcher._work"
        in graph["thread_roots"]
    )
    assert (
        "sboxgates_tpu.resilience.deadline:run_with_deadline.<locals>.work"
        in graph["thread_roots"]
    )
    assert graph["jit_roots"], "jit-boundary roots missing"
    assert graph["edges"], "call graph has no edges"
    edge_keys = set(graph["edges"][0])
    assert {"caller", "callee", "locked", "in_loop"} <= edge_keys
    # the canonical transitive path exists edge by edge
    pairs = {(e["caller"], e["callee"]) for e in graph["edges"]}
    pre = "sboxgates_tpu.ops.combinatorics:ChunkPrefetcher."
    assert (pre + "_work", pre + "_produce_one") in pairs
    assert (
        pre + "_produce_one",
        "sboxgates_tpu.ops.combinatorics:CombinationStream.next_chunk",
    ) in pairs
