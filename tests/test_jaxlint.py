"""Tier-1 gate: the shipped tree holds at zero unsuppressed jaxlint
findings.

This self-scan is the regression net the static pass exists for: any PR
that introduces a recompile hazard, a hot-loop sync, a tracer escape, a
lockless thread mutation, or a swallowed exception — without either
fixing it or justifying it inline — fails here.
"""

import json
import os
import subprocess
import sys

from sboxgates_tpu.analysis import lint_paths, load_config

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_package_scan_has_zero_unsuppressed_findings():
    config = load_config(ROOT)
    reports = lint_paths(config=config)
    findings = [f for r in reports for f in r.findings]
    assert findings == [], "\n".join(f.render() for f in findings)
    # sanity: the scan actually covered the package and the inline
    # suppressions are present (each carries a mandatory reason)
    assert len(reports) > 20
    assert sum(len(r.suppressed) for r in reports) > 0


def test_config_comes_from_pyproject():
    config = load_config(ROOT)
    assert config.rules == [
        "R1", "R2", "R3", "R4", "R5", "R6",
        "R1x", "R2x", "R4x", "R7", "R8", "R9",
        "R10", "R11", "R12", "R13", "R14", "R15",
    ]
    assert config.whole_program  # cross-module pass is on in the gate
    assert "sboxgates_tpu/search/lut.py" in config.hot_modules
    assert config.is_hot("sboxgates_tpu/ops/sweeps.py")
    assert not config.is_hot("sboxgates_tpu/search/context.py")
    # contract-pass configuration (R7/R8/R9)
    assert config.is_dispatch("sboxgates_tpu/search/lut.py")
    assert config.is_dispatch("sboxgates_tpu/ops/sweeps.py")
    assert not config.is_dispatch("sboxgates_tpu/telemetry/metrics.py")
    assert "bucket_size" in config.bucket_sources
    assert "guarded_dispatch" in config.blocking_calls
    # protocol/determinism/durability configuration (R10/R11/R12)
    assert "process_index" in config.rank_sources
    assert "breach_verdict" in config.agreement_sites
    assert "journal.append" in config.deterministic_sinks
    assert config.is_durable("sboxgates_tpu/resilience/checkpoint.py")
    assert config.is_durable("sboxgates_tpu/store/store.py")
    assert not config.is_durable("sboxgates_tpu/search/lut.py")
    assert "durable_write_text" in config.durable_helpers
    assert any(
        w.startswith("native.devcb:") for w in config.chaos_waivers
    )
    # trust-boundary configuration (R13/R14/R15)
    assert config.is_handler("sboxgates_tpu/serve_net/server.py")
    assert not config.is_handler("sboxgates_tpu/search/lut.py")
    assert "headers.get" in config.untrusted_sources
    assert "rfile.read" in config.untrusted_sources
    assert "blake2b" in config.sanitizers
    assert "path.join" in config.trust_sinks
    assert "authenticate" in config.auth_sites
    assert "active_jobs" in config.quota_sites
    assert "journal.admit" in config.journal_sites
    assert "orch.submit" in config.effect_sites
    assert "_send_json" in config.response_sites
    assert "Thread" in config.resource_ctors
    assert "drain_hooks" in config.teardown_registries


def test_committed_baseline_is_zero_findings():
    path = os.path.join(ROOT, "jaxlint_baseline.json")
    with open(path, "r", encoding="utf-8") as f:
        baseline = json.load(f)
    assert baseline["findings"] == []


def test_cli_exits_zero_and_emits_json_and_sarif(tmp_path):
    """One subprocess scan covers both machine formats: the JSON
    payload and (--sarif rides the same scan, costing no extra pass)
    the SARIF 2.1.0 export — named driver, full rule catalog, zero
    results on the clean tree."""
    sarif = tmp_path / "scan.sarif"
    proc = subprocess.run(
        [
            sys.executable, "-m", "sboxgates_tpu.analysis",
            "--format", "json", "--sarif", str(sarif),
        ],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert payload["files_scanned"] > 20
    doc = json.loads(sarif.read_text(encoding="utf-8"))
    assert doc["version"] == "2.1.0"
    driver = doc["runs"][0]["tool"]["driver"]
    assert driver["name"] == "jaxlint"
    rule_ids = {r["id"] for r in driver["rules"]}
    assert {"R1", "R7", "R10", "R11", "R12", "R13", "R14", "R15"} <= rule_ids
    for r in driver["rules"]:
        assert r["shortDescription"]["text"]
    # the shipped tree is clean, so the run carries no results
    assert doc["runs"][0]["results"] == []


def test_cli_baseline_mode_passes():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "sboxgates_tpu.analysis",
            "--baseline",
            "jaxlint_baseline.json",
        ],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_whole_program_pass_runs_in_gate_and_under_budget(monkeypatch):
    """The shared AST cache keeps the full whole-program scan (per-file
    rules + call graph + every cross-module pass through R12) inside
    the CI budget.  The structural guard is the real regression net:
    each module is parsed EXACTLY once, however many passes run over it
    — re-parsing per pass is what would blow the wall clock on a big
    tree.  Measured 2026-08: ~7.3 s for 75 files with all 18 rules on
    (the taint/dominance/lifecycle passes added ~2.7 s even after the
    handler-only source scan, single-pass reach seeding, and inert-
    function pruning); the 15 s ceiling tolerates a ~2x-loaded CI
    host."""
    import ast
    import time

    calls = {"n": 0}
    real_parse = ast.parse

    def counting_parse(*args, **kwargs):
        calls["n"] += 1
        return real_parse(*args, **kwargs)

    monkeypatch.setattr(ast, "parse", counting_parse)
    config = load_config(ROOT)
    assert config.whole_program
    t0 = time.monotonic()
    reports = lint_paths(config=config)
    elapsed = time.monotonic() - t0
    assert calls["n"] == len(reports), (
        f"{calls['n']} ast.parse calls for {len(reports)} files — the "
        "whole-program pass must share one parse per module"
    )
    if elapsed >= 15.0:
        # A transient load spike shouldn't flake the gate: retry once
        # and hold the best of the two runs to the budget.
        t0 = time.monotonic()
        lint_paths(config=config)
        elapsed = min(elapsed, time.monotonic() - t0)
    assert elapsed < 15.0, f"whole-program lint took {elapsed:.1f}s"
    # The cross-module pass really ran: the acknowledged-source R2x
    # entries (deliberate compact-verdict syncs) only exist under it,
    # and the contract passes' acknowledged sites only exist under R7.
    sup_rules = {f.rule for r in reports for f in r.suppressed}
    assert "R2x" in sup_rules
    assert "R7" in sup_rules
    # Rule-registry parity for the trust-boundary passes: every report
    # records R13/R14/R15 as checked (so their inline markers are
    # judged for staleness), and the acknowledged serve_net sites —
    # the verbatim-journaled idempotency key, the replay/join/re-ack
    # paths — only appear when those passes actually execute in the
    # default config.
    assert all(
        {"R13", "R14", "R15"} <= r.checked for r in reports
    ), "trust-boundary rules missing from the checked registry"
    assert "R13" in sup_rules
    assert "R14" in sup_rules


def test_whole_program_json_is_deterministic():
    """Two scans of the same tree are byte-identical (sorted traversal
    everywhere — an unsorted dict walk would flake the baseline gate)."""
    outs = []
    for _ in range(2):
        proc = subprocess.run(
            [
                sys.executable, "-m", "sboxgates_tpu.analysis",
                "--format", "json",
            ],
            cwd=ROOT,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        outs.append(proc.stdout)
    assert outs[0] == outs[1]


def test_cli_graph_dump():
    """--graph emits the resolved call graph as deterministic JSON:
    functions, lock/loop-annotated edges, thread and jit roots."""
    outs = []
    for _ in range(2):
        proc = subprocess.run(
            [sys.executable, "-m", "sboxgates_tpu.analysis", "--graph"],
            cwd=ROOT,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        outs.append(proc.stdout)
    assert outs[0] == outs[1]
    graph = json.loads(outs[0])
    assert (
        "sboxgates_tpu.ops.combinatorics:ChunkPrefetcher._work"
        in graph["thread_roots"]
    )
    assert (
        "sboxgates_tpu.resilience.deadline:run_with_deadline.<locals>.work"
        in graph["thread_roots"]
    )
    assert graph["jit_roots"], "jit-boundary roots missing"
    assert graph["edges"], "call graph has no edges"
    edge_keys = set(graph["edges"][0])
    assert {"caller", "callee", "locked", "in_loop"} <= edge_keys
    # the canonical transitive path exists edge by edge
    pairs = {(e["caller"], e["callee"]) for e in graph["edges"]}
    pre = "sboxgates_tpu.ops.combinatorics:ChunkPrefetcher."
    assert (pre + "_work", pre + "_produce_one") in pairs
    assert (
        pre + "_produce_one",
        "sboxgates_tpu.ops.combinatorics:CombinationStream.next_chunk",
    ) in pairs


def test_lock_order_graph_covers_every_thread_root():
    """R9's lock graph rides the --graph dump: every pinned/auto thread
    root has a (possibly empty) transitive lock-acquisition set, the
    known worker-lock relationships are present, and the shipped tree
    has no acquisition-order cycle."""
    proc = subprocess.run(
        [sys.executable, "-m", "sboxgates_tpu.analysis", "--graph"],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    graph = json.loads(proc.stdout)
    lo = graph["lock_order"]
    assert lo["cycles"] == []
    # EVERY thread root is covered by the analysis.
    assert set(lo["root_acquires"]) == set(graph["thread_roots"])
    acq = lo["root_acquires"]
    warmer = "sboxgates_tpu.search.warmup:KernelWarmer._work"
    assert (
        "sboxgates_tpu.search.warmup:KernelWarmer._cv" in acq[warmer]
    ), "the warmer's condition variable must be in its lock set"
    prefetch = "sboxgates_tpu.ops.combinatorics:ChunkPrefetcher._work"
    assert (
        "sboxgates_tpu.ops.combinatorics._native_probe_lock"
        in acq[prefetch]
    ), "the PR 4 native-probe lock must be visible from the prefetcher"
    # The order edges exist and name real sites.
    assert lo["edges"], "lock-order graph has no edges"
    for e in lo["edges"][:3]:
        assert {"from", "to", "path", "line", "note"} <= set(e)


def test_every_thread_creation_is_pinned():
    """The R7 pin gate holds on the shipped tree: every
    threading.Thread(target=...) creation resolves to a function pinned
    in [tool.jaxlint] thread_roots, and every pin matches a function
    (the stale run_fleet_circuits.worker pin from PR 8's refactor is
    the regression this guards against)."""
    from sboxgates_tpu.analysis.callgraph import spec_matches_function
    from sboxgates_tpu.analysis.project import lint_project

    config = load_config(ROOT)
    _reports, graph = lint_project(config=config, return_graph=True)
    assert graph.thread_creations, "no Thread creations found"
    for tc in graph.thread_creations:
        assert tc.targets, f"unresolved Thread target at {tc.path}:{tc.line}"
        assert any(
            spec_matches_function(spec, t)
            for spec in config.thread_roots
            for t in tc.targets
        ), f"unpinned Thread target {tc.targets} at {tc.path}:{tc.line}"
    for spec in config.thread_roots:
        assert any(
            spec_matches_function(spec, key) for key in graph.functions
        ), f"stale thread_roots pin {spec!r}"


def test_sarif_results_carry_physical_locations(tmp_path):
    """On a dirty tree the SARIF results pin rule, level, and the
    file/line/column of every finding."""
    repo = tmp_path / "proj"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    (repo / "pyproject.toml").write_text(
        "[tool.jaxlint]\n"
        'paths = ["pkg"]\n'
        'rules = ["R5"]\n'
        "whole_program = false\n"
    )
    (pkg / "a.py").write_text(
        "def f():\n"
        "    try:\n"
        "        probe()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    out = repo / "scan.sarif"
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable, "-m", "sboxgates_tpu.analysis",
            "--sarif", str(out),
        ],
        cwd=repo,
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(out.read_text(encoding="utf-8"))
    results = doc["runs"][0]["results"]
    assert [r["ruleId"] for r in results] == ["R5"]
    assert results[0]["level"] == "warning"
    assert results[0]["message"]["text"]
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "pkg/a.py"
    assert loc["region"]["startLine"] == 4
    assert loc["region"]["startColumn"] >= 1


def test_sarif_marks_baseline_matches_as_external_suppressions(tmp_path):
    """A finding the committed --baseline already accounts for still
    appears in the SARIF log (complete scan record) but carries a
    ``suppressions`` entry of kind ``external`` (SARIF 2.1.0 §3.27.23),
    so CI annotators surface only genuinely new results.  Regression:
    the export used to emit baseline-matched findings unmarked."""
    repo = tmp_path / "proj"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    (repo / "pyproject.toml").write_text(
        "[tool.jaxlint]\n"
        'paths = ["pkg"]\n'
        'rules = ["R5"]\n'
        "whole_program = false\n"
    )
    body = (
        "def f():\n"
        "    try:\n"
        "        probe()\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        probe()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    (pkg / "a.py").write_text(body)
    # Baseline accounts for the FIRST finding only; the second is new.
    (repo / "base.json").write_text(json.dumps({
        "schema": 1,
        "findings": [{"path": "pkg/a.py", "line": 4, "rule": "R5"}],
    }))
    out = repo / "scan.sarif"
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable, "-m", "sboxgates_tpu.analysis",
            "--baseline", "base.json", "--sarif", str(out),
        ],
        cwd=repo,
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr  # one new
    doc = json.loads(out.read_text(encoding="utf-8"))
    results = doc["runs"][0]["results"]
    assert [r["ruleId"] for r in results] == ["R5", "R5"]
    by_line = {
        r["locations"][0]["physicalLocation"]["region"]["startLine"]: r
        for r in results
    }
    assert by_line[4]["suppressions"] == [{"kind": "external"}]
    assert "suppressions" not in by_line[8]


def test_chaos_coverage_gate():
    """Tier-1: every fault site declared in faults.KNOWN_SITES is
    either armed by a chaos test (SBG_FAULTS spec or faults.arm) or
    carries a reasoned waiver in [tool.jaxlint] chaos_waivers — and no
    waiver is stale.  In-process (the CLI --coverage path is the same
    chaos_coverage call) to keep the gate off the subprocess-scan
    budget."""
    from sboxgates_tpu.analysis.durability import chaos_coverage
    from sboxgates_tpu.analysis.project import lint_project

    config = load_config(ROOT)
    _reports, graph = lint_project(config=config, return_graph=True)
    report = chaos_coverage(graph, config)
    assert report["uncovered"] == []
    assert report["stale_waivers"] == []
    assert report["declared_total"] >= 18
    assert report["armed_total"] >= 17
    # the hardware-only site is documented as waived, not dropped —
    # and quoting its name in THIS test must not count as arming it
    assert report["sites"]["native.devcb"]["waiver"]
    assert report["sites"]["native.devcb"]["armed_by"] == []
    # a representative chaos site really is armed by the test tree
    assert report["sites"]["ckpt.replace"]["armed_by"]


def test_bare_site_names_arm_only_with_fault_plumbing():
    """The bare-constant fallback exists for parametrized site lists
    whose spec is built in an f-string — those files always carry real
    fault plumbing.  A site name quoted anywhere else (an assertion, a
    docstring) arms nothing."""
    from sboxgates_tpu.analysis.durability import _scan_test_source

    declared = {"ckpt.replace"}
    quoted = 'def test_gate():\n    assert sites["ckpt.replace"]\n'
    assert _scan_test_source(quoted, declared) == set()
    docstring = '"""mentions SBG_FAULTS specs."""\nx = "ckpt.replace"\n'
    assert _scan_test_source(docstring, declared) == set()
    plumbed = (
        "import os\n"
        "def test_crash(site):\n"
        '    os.environ["SBG_FAULTS"] = f"{site}:crash@2"\n'
        '    run("ckpt.replace")\n'
    )
    assert _scan_test_source(plumbed, declared) == {"ckpt.replace"}


def _git(repo, *argv):
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *argv],
        cwd=repo,
        check=True,
        capture_output=True,
    )


def _diff_base_repo(tmp_path):
    """A tiny git project whose HEAD carries exactly one R5 finding."""
    repo = tmp_path / "proj"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    (repo / "pyproject.toml").write_text(
        "[tool.jaxlint]\n"
        'paths = ["pkg"]\n'
        'rules = ["R5"]\n'
        "whole_program = false\n"
    )
    (pkg / "a.py").write_text(
        "def old():\n"
        "    try:\n"
        "        probe()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    _git(repo, "init", "-q")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-q", "-m", "base")
    return repo


def _run_diff_base(repo, *extra, ref="HEAD"):
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [
            sys.executable, "-m", "sboxgates_tpu.analysis",
            "--diff-base", ref, "--format", "json", *extra,
        ],
        cwd=repo,
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_diff_base_reports_only_new_findings(tmp_path):
    """--diff-base REF: only findings introduced since REF are
    reported (exit 1); the pre-existing finding stays invisible even
    though the full scan still counts it."""
    repo = _diff_base_repo(tmp_path)
    src = (repo / "pkg" / "a.py").read_text()
    (repo / "pkg" / "a.py").write_text(
        "def pad():\n    return 0\n\n\n" + src +
        "\n\ndef fresh():\n"
        "    try:\n"
        "        probe()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    proc = _run_diff_base(repo)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["diff_base"] == "HEAD"
    assert payload["total_findings"] == 2
    new = payload["new_findings"]
    # Only the fresh swallow is new: the old one moved four lines down
    # (the findings are matched on source-line TEXT, not line numbers,
    # so unrelated edits above it cannot resurrect it).
    assert [(f["rule"], f["path"]) for f in new] == [("R5", "pkg/a.py")]
    assert new[0]["line"] == 15  # the fresh except line, not the old one


def test_diff_base_clean_when_tree_matches_ref(tmp_path):
    repo = _diff_base_repo(tmp_path)
    proc = _run_diff_base(repo)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["new_findings"] == []
    assert payload["total_findings"] == 1


def test_diff_base_bad_ref_is_a_one_line_error(tmp_path):
    repo = _diff_base_repo(tmp_path)
    proc = _run_diff_base(repo, ref="no-such-ref")
    assert proc.returncode == 2
    assert "no-such-ref" in proc.stderr


def test_diff_base_handles_dot_scan_paths(tmp_path):
    """paths = ["."] must match every file at the base ref too — a
    mis-filtered base tree would report every pre-existing finding as
    new."""
    repo = tmp_path / "proj"
    repo.mkdir()
    (repo / "pyproject.toml").write_text(
        "[tool.jaxlint]\n"
        'paths = ["."]\n'
        'rules = ["R5"]\n'
        "whole_program = false\n"
    )
    (repo / "a.py").write_text(
        "def old():\n"
        "    try:\n"
        "        probe()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    _git(repo, "init", "-q")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-q", "-m", "base")
    proc = _run_diff_base(repo)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["new_findings"] == []
    assert payload["total_findings"] == 1


def test_diff_base_smoke_on_shipped_tree():
    """``--diff-base HEAD~1`` exits 0 on the shipped repo: the working
    tree scans clean (the self-scan gate above), so no finding can be
    new relative to ANY base ref — including one whose checked-out
    config predates the newest rules (old code is judged by the current
    configuration, per the CLI contract)."""
    proc = subprocess.run(
        [
            sys.executable, "-m", "sboxgates_tpu.analysis",
            "--diff-base", "HEAD~1", "--format", "json",
        ],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["diff_base"] == "HEAD~1"
    assert payload["new_findings"] == []


def test_list_rules_covers_trust_boundary_passes():
    """--list-rules documents every registered rule, including the
    R13/R14/R15 trust-boundary passes."""
    proc = subprocess.run(
        [sys.executable, "-m", "sboxgates_tpu.analysis", "--list-rules"],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for rid, hint in (
        ("R13", "taint"),
        ("R14", "admission"),
        ("R15", "release"),
    ):
        line = next(
            (ln for ln in proc.stdout.splitlines() if ln.startswith(rid)),
            None,
        )
        assert line is not None, f"{rid} missing from --list-rules"
        assert hint in line.lower(), line
