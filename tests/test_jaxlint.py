"""Tier-1 gate: the shipped tree holds at zero unsuppressed jaxlint
findings.

This self-scan is the regression net the static pass exists for: any PR
that introduces a recompile hazard, a hot-loop sync, a tracer escape, a
lockless thread mutation, or a swallowed exception — without either
fixing it or justifying it inline — fails here.
"""

import json
import os
import subprocess
import sys

from sboxgates_tpu.analysis import lint_paths, load_config

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_package_scan_has_zero_unsuppressed_findings():
    config = load_config(ROOT)
    reports = lint_paths(config=config)
    findings = [f for r in reports for f in r.findings]
    assert findings == [], "\n".join(f.render() for f in findings)
    # sanity: the scan actually covered the package and the inline
    # suppressions are present (each carries a mandatory reason)
    assert len(reports) > 20
    assert sum(len(r.suppressed) for r in reports) > 0


def test_config_comes_from_pyproject():
    config = load_config(ROOT)
    assert config.rules == ["R1", "R2", "R3", "R4", "R5"]
    assert "sboxgates_tpu/search/lut.py" in config.hot_modules
    assert config.is_hot("sboxgates_tpu/ops/sweeps.py")
    assert not config.is_hot("sboxgates_tpu/search/context.py")


def test_committed_baseline_is_zero_findings():
    path = os.path.join(ROOT, "jaxlint_baseline.json")
    with open(path, "r", encoding="utf-8") as f:
        baseline = json.load(f)
    assert baseline["findings"] == []


def test_cli_exits_zero_and_emits_json():
    proc = subprocess.run(
        [sys.executable, "-m", "sboxgates_tpu.analysis", "--format", "json"],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert payload["files_scanned"] > 20


def test_cli_baseline_mode_passes():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "sboxgates_tpu.analysis",
            "--baseline",
            "jaxlint_baseline.json",
        ],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
