"""Concurrent mux-branch exploration (Options.parallel_mux).

The step-5 select-bit branches are independent state copies folded in bit
order, so running them as rendezvous threads must (a) be deterministic for
a fixed seed, (b) produce byte-identical circuits to the serial loop when
randomization is off, and (c) always produce valid circuits.
"""

import os

from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import NO_GATE, SAT, State
from sboxgates_tpu.graph.xmlio import state_fingerprint
from sboxgates_tpu.search import (
    Options,
    SearchContext,
    generate_graph_one_output,
    make_targets,
)
from sboxgates_tpu.utils.sbox import load_sbox

DATA = os.path.join(os.path.dirname(__file__), "data")


def _search(path, output=0, **kw):
    sbox, n = load_sbox(path)
    targets = make_targets(sbox)
    ctx = SearchContext(Options(**kw))
    st = State.init_inputs(n)
    results = generate_graph_one_output(
        ctx, st, targets, output, save_dir=None, log=lambda s: None
    )
    assert results
    best = results[-1]
    mask = tt.mask_table(n)
    gid = best.outputs[output]
    assert gid != NO_GATE
    assert bool(
        tt.eq_mask(best.table(gid), tt.target_table(sbox, output), mask)
    )
    return ctx, best


def test_parallel_mux_deterministic():
    """Two runs with the same seed must produce identical circuits even
    though branch threads race: per-branch PRNG streams are pre-seeded and
    results fold in bit order."""
    a_ctx, a = _search(
        os.path.join(DATA, "des_s1.txt"), seed=9, lut_graph=True,
        parallel_mux=True,
    )
    b_ctx, b = _search(
        os.path.join(DATA, "des_s1.txt"), seed=9, lut_graph=True,
        parallel_mux=True,
    )
    assert a_ctx.rdv is not None  # concurrency actually enabled
    assert state_fingerprint(a) == state_fingerprint(b)


def test_parallel_mux_matches_serial_when_not_randomized():
    """With randomize off every kernel selection is deterministic and
    independent of the PRNG, so the concurrent fold must reproduce the
    serial loop's circuit exactly."""
    _, par = _search(
        os.path.join(DATA, "crypto1_fa.txt"), randomize=False, seed=1,
        parallel_mux=True,
    )
    _, ser = _search(
        os.path.join(DATA, "crypto1_fa.txt"), randomize=False, seed=1,
        parallel_mux=False,
    )
    assert state_fingerprint(par) == state_fingerprint(ser)


def test_run_group_slices_oversized_batches(monkeypatch):
    """Groups larger than the biggest vmap bucket (32) must be dispatched
    in slices, not crash on the padded-results indexing."""
    import numpy as np

    from sboxgates_tpu.search import batched

    monkeypatch.setattr(batched, "_PAD_IS_CHEAP", True)
    rdv = batched.Rendezvous(1)

    import jax.numpy as jnp

    def kern(x):
        return jnp.stack([x, x + 1])

    entries = [
        {"key": "k", "kernel": kern, "args": (np.int32(i),), "shared": (),
         "done": False}
        for i in range(40)
    ]
    rdv._run_group("k", entries)
    for i, e in enumerate(entries):
        assert list(e["result"]) == [i, i + 1]


def test_run_mux_jobs_inline_error_joins_children(monkeypatch):
    """An exception in an inline job must still join spawned children
    (who may be blocked in a rendezvous submit) before propagating."""
    import numpy as np
    import pytest

    import jax.numpy as jnp

    from sboxgates_tpu.search import batched

    monkeypatch.setattr(batched.Rendezvous, "MAX_SPAWNED", 1)
    ctx = SearchContext(Options(seed=1, parallel_mux=True))
    rdv = ctx.rdv

    def sweeping_job(cctx):
        # Blocks in rdv.submit until the pool quiesces — deadlocks
        # forever if the inline error path skips the suspend/join.
        # (Direct submit: the synthetic kernel is not a warmup-registry
        # entry, and the blocking behavior under test lives here.)
        v = cctx.rdv.submit(
            ("t",), lambda x: jnp.stack([x, x]), (np.int32(3),), ()
        )
        return int(v[0])

    def bad_job(cctx):
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        batched.run_mux_jobs(ctx, [sweeping_job, bad_job])
    assert rdv.live == 1
    assert rdv.spawned == 0


def test_parallel_mux_gate_mode_sat():
    """Gate-mode SAT search (the reference's .travis.yml:40 config shape)
    under concurrency: valid circuit, sweeps actually batched.  Forces the
    device-kernel path (host_small_steps=False) — natively-routed nodes
    deliberately bypass the rendezvous (SearchContext.uses_native_step)."""
    ctx, best = _search(
        os.path.join(DATA, "crypto1_fa.txt"), seed=5, metric=SAT,
        try_nots=True, parallel_mux=True, host_small_steps=False,
    )
    assert best.sat_metric > 0
    assert ctx.rdv.stats["dispatches"] <= ctx.rdv.stats["submits"]
    assert ctx.rdv.stats["batched_rows"] > 0  # some sweeps merged


def test_native_nodes_skip_mux_threads():
    """Small gate-mode states route node sweeps to the native runtime and
    must not submit anything to the rendezvous — the threads' only value
    is overlapping device round trips, which native nodes don't make."""
    import pytest

    from sboxgates_tpu import native

    if not native.available():
        pytest.skip(f"native lib unavailable: {native.build_error()}")
    ctx, best = _search(
        os.path.join(DATA, "crypto1_fa.txt"), seed=5, metric=SAT,
        try_nots=True, parallel_mux=True,
    )
    assert best.sat_metric > 0
    assert ctx.uses_native_step(best)
    assert ctx.rdv.stats["submits"] == 0
    # Gate mode runs in the native engine (one C call for the whole
    # recursion); with it opted out, the per-node native step runs.
    assert (
        ctx.prof.calls.get("gate_engine_native", 0) > 0
        or ctx.prof.calls.get("gate_step_native", 0) > 0
    )
