"""The bench watchdog's breach path: a hung entry (the observed failure
mode — tunnel dies mid-run, XLA RPC blocks forever) must salvage the
partial capture and still print the driver-facing headline line.

The no-breach path is exercised by every SBG_BENCH_SMOKE run; this test
forces a breach by monkeypatching a bench entry into an infinite sleep
with a tiny budget, in a subprocess (the watchdog exits via os._exit).
"""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def test_watchdog_salvages_partial_and_prints_headline(tmp_path):
    code = """
import json, os, sys, time
sys.path.insert(0, {repo!r})
os.environ["SBG_BENCH_SMOKE"] = "1"
import bench

bench.HERE = {out!r}          # keep salvage artifacts out of the repo
bench.ENTRY_BUDGET_S = 1.0    # breach fast

def hang():
    # Stands in for a blocked device RPC: never returns, not
    # interruptible by anything but process exit.
    while True:
        time.sleep(1)

# First entry hangs; nothing else should ever run.
bench.bench_cpu_baseline = hang
bench.main()
"""
    r = subprocess.run(
        [sys.executable, "-c",
         code.format(repo=os.path.dirname(HERE), out=str(tmp_path))],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    # Watchdog exit, not a hang and not a clean completion.
    assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
    # The salvage file exists and records the aborted entry.
    aborted = json.load(open(tmp_path / "BENCH_ABORTED.json"))
    assert any("watchdog" in e.get("error", "") for e in aborted), aborted
    # The driver-facing line is still a single valid JSON object with
    # the headline metric name and an abort explanation.
    lines = [l for l in r.stdout.splitlines() if l.strip().startswith("{")]
    assert lines, r.stdout
    head = json.loads(lines[-1])
    assert head["metric"] == "lut5_candidates_per_sec_per_chip_aes"
    assert head["value"] is None  # the headline entry never ran
    assert "aborted" in head["error"]
    # A breached smoke run must never promote its partial file to the
    # completed BENCH_SMOKE.json.
    assert not (tmp_path / "BENCH_SMOKE.json").exists()
