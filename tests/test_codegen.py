"""Codegen/export layer: DOT, C/CUDA emission, and the execution backends.

The reference validates emitted code by recompiling it (.travis.yml:44-51);
here the emitted C is compiled with gcc and *executed* against the S-box,
and the jnp/Pallas/native executors are checked against truth tables.
"""

import os
import subprocess
import tempfile

import numpy as np
import pytest

from sboxgates_tpu import native
from sboxgates_tpu.codegen import (
    c_function_text,
    compile_circuit,
    digraph_text,
    eval_sbox,
    execute_native,
)
from sboxgates_tpu.codegen.pallas_kernel import compile_pallas
from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import GATES, State
from sboxgates_tpu.search import (
    Options,
    SearchContext,
    generate_graph,
    make_targets,
)
from sboxgates_tpu.utils.sbox import load_sbox

DATA = os.path.join(os.path.dirname(__file__), "data")


def _search_circuit(path, lut=False, seed=3):
    sbox, n = load_sbox(path)
    targets = make_targets(sbox)
    st = State.init_inputs(n)
    ctx = SearchContext(Options(seed=seed, lut_graph=lut))
    res = generate_graph(ctx, st, targets, save_dir=None, log=lambda s: None)
    assert res
    return res[-1], sbox, n


@pytest.fixture(scope="module")
def fa_circuit():
    return _search_circuit(os.path.join(DATA, "crypto1_fa.txt"))


@pytest.fixture(scope="module")
def fa_lut_circuit():
    return _search_circuit(os.path.join(DATA, "crypto1_fa.txt"), lut=True)


def test_digraph_format(fa_circuit):
    st, _, n = fa_circuit
    text = digraph_text(st)
    assert text.startswith("digraph sbox {\n")
    assert text.endswith("}\n")
    for i in range(n):
        assert f'gt{i} [label="IN {i}"];' in text
    assert "-> out0;" in text


def test_digraph_lut_label(fa_lut_circuit):
    st, _, _ = fa_lut_circuit
    text = digraph_text(st)
    assert any(g.type == bf.LUT for g in st.gates)
    lut_gid = next(i for i, g in enumerate(st.gates) if g.type == bf.LUT)
    assert (
        f'gt{lut_gid} [label="0x%02x"];' % st.gates[lut_gid].function in text
    )


def test_eval_sbox_matches(fa_circuit):
    st, sbox, n = fa_circuit
    got = eval_sbox(st)
    # circuit realizes output bit 0 only
    assert ((got ^ sbox[: 1 << n]) & 1 == 0).all()


def test_execute_native_matches_tables(fa_lut_circuit):
    if not native.available():
        pytest.skip("native runtime unavailable")
    st, _, _ = fa_lut_circuit
    out = execute_native(st)
    assert (out == st.live_tables()).all()


def test_pallas_interpret_matches_jnp(fa_lut_circuit):
    st, _, n = fa_lut_circuit
    rng = np.random.default_rng(0)
    jnp_fn = compile_circuit(st)
    pl_fn = compile_pallas(st, block=1024, interpret=True)
    # 2048 = whole blocks; 300 exercises the internal pad-and-slice path
    for w in (2048, 300):
        inputs = rng.integers(0, 2**32, size=(n, w), dtype=np.uint32)
        a = np.asarray(jnp_fn(inputs))
        b = np.asarray(pl_fn(inputs))
        assert b.shape == a.shape
        assert (a == b).all()


def test_emitted_c_compiles_and_runs(fa_circuit):
    """gcc-compile the emitted C and execute all 2^n inputs against the
    S-box (stronger than the reference's compile-only CI check)."""
    st, sbox, n = fa_circuit
    src = c_function_text(st)
    assert src.startswith("typedef unsigned long long int bit_t;")
    harness = """
#include <stdio.h>
%s
int main(void) {
  for (int x = 0; x < (1 << %d); x++) {
    bits in;
%s
    unsigned long long r = s0(in);
    printf("%%d\\n", (int)(r & 1));
  }
  return 0;
}
""" % (
        src,
        n,
        "\n".join(f"    in.b{i} = (x >> {i}) & 1;" for i in range(n)),
    )
    with tempfile.TemporaryDirectory() as tmp:
        cpath = os.path.join(tmp, "c.c")
        with open(cpath, "w") as f:
            f.write(harness)
        exe = os.path.join(tmp, "c.bin")
        subprocess.run(
            ["gcc", "-Wall", "-Wpedantic", "-Werror", "-o", exe, cpath],
            check=True,
            capture_output=True,
        )
        out = subprocess.run([exe], check=True, capture_output=True, text=True)
    got = np.array([int(x) for x in out.stdout.split()], dtype=np.uint8)
    assert (got == (sbox[: 1 << n] & 1)).all()


def test_emitted_cuda_format(fa_lut_circuit):
    """The reference's CI compiles its emitted CUDA with nvcc
    (.travis.yml:49-51); no nvcc exists in this image, so by default
    this asserts the CUDA-specific constructs instead — a toolchain
    limitation, not a policy: when nvcc IS present, the emitted source
    is nvcc-compiled too."""
    st, _, _ = fa_lut_circuit
    src = c_function_text(st)
    assert src.startswith("#define LUT(a,b,c,d,e)")
    assert "lop3.b32" in src
    assert "__device__ __forceinline__" in src
    assert "typedef int bit_t;" in src
    import shutil

    if shutil.which("nvcc"):
        with tempfile.TemporaryDirectory() as tmp:
            cu = os.path.join(tmp, "s.cu")
            with open(cu, "w") as f:
                f.write(src + "\n")
            subprocess.run(
                ["nvcc", "-c", "-o", os.path.join(tmp, "s.o"), cu],
                check=True,
                capture_output=True,
            )


def test_multi_output_signature():
    """Two outputs -> pointer-return void signature (convert_graph.c:162-169)."""
    st = State.init_inputs(3)
    a = st.add_gate(bf.AND, 0, 1, GATES)
    x = st.add_gate(bf.XOR, a, 2, GATES)
    st.outputs[0] = a
    st.outputs[1] = x
    src = c_function_text(st)
    assert "void s(bits in, bit_t *out0, bit_t *out1)" in src
    assert "*out1 = " in src


def test_no_outputs_raises():
    st = State.init_inputs(2)
    st.add_gate(bf.AND, 0, 1, GATES)
    with pytest.raises(ValueError):
        c_function_text(st)


def test_single_output_lut_declares_return_var(fa_lut_circuit):
    """Regression: a LUT gate that is the single output must still declare
    its variable before the LUT macro writes it."""
    st, _, _ = fa_lut_circuit
    gid = st.outputs[0]
    if st.gates[gid].type != bf.LUT:
        pytest.skip("search did not end on a LUT gate")
    src = c_function_text(st)
    assert "bit_t out0; LUT(out0," in src
    assert "  return out0;" in src
