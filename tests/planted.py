"""Shared fixture: a 50-gate state with a planted 5-LUT decomposition.

Used by the sharded-pivot equivalence test, the 2-process distributed test,
and its worker subprocess — one construction so the cross-process
verification can never drift out of sync with what the worker searched.
"""

import numpy as np

from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import GATES, State

PLANT_OUTER = 0x2D
PLANT_INNER = 0xB4
PLANT_OUTER_GATES = (12, 26, 41)
PLANT_INNER_GATES = (19, 33)


def build_planted_lut5():
    """(state, target, mask): 8 inputs + XOR gates up to 50 total, with a
    target realizable as LUT(LUT(g12,g26,g41), g19, g33) — large enough that
    C(50,5) crosses the pivot-path threshold."""
    rng = np.random.default_rng(5)
    st = State.init_inputs(8)
    while st.num_gates < 50:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    a, b, c = PLANT_OUTER_GATES
    d, e = PLANT_INNER_GATES
    outer = tt.eval_lut(PLANT_OUTER, st.table(a), st.table(b), st.table(c))
    target = tt.eval_lut(PLANT_INNER, outer, st.table(d), st.table(e))
    return st, target, tt.mask_table(8)


def build_planted_lut5_small(g: int = 24):
    """Like :func:`build_planted_lut5` but below the pivot threshold, so a
    mesh search takes the chunked feasible-stream path (the multi-host
    compacted-gather code) instead of the pivot tiles."""
    rng = np.random.default_rng(5)
    st = State.init_inputs(8)
    while st.num_gates < g:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    a, b, c = 9, 12, 17
    d, e = 10, 20
    outer = tt.eval_lut(PLANT_OUTER, st.table(a), st.table(b), st.table(c))
    target = tt.eval_lut(PLANT_INNER, outer, st.table(d), st.table(e))
    return st, target, tt.mask_table(8)


PLANT7_OUTER = 0x96
PLANT7_MIDDLE = 0xE8
PLANT7_INNER = 0xCA


def build_planted_lut7(gates: int = 24):
    """(state, target, mask): ``gates`` mixed-gate state (8 inputs) with
    a target realizable as LUT(LUT(9,12,17), LUT(10,15,21), 19).
    C(gates, 7) exceeds the fused-head single-chunk limit (2^17) for
    every ``gates`` >= 22, so the search takes the staged path, and
    stage A collects enough feasible tuples to pass every host-solve
    threshold, forcing the sharded stage-B device solver.  The default
    24 (C(24,7) = 346k) is the historical shape; ``gates=22`` (C(22,7)
    = 171k) halves stage-A work for the tier-1 walks that only need the
    staged routing, not the bigger space."""
    assert gates >= 22, "below 22 gates the 7-LUT space fits one chunk"
    rng = np.random.default_rng(3)
    st = State.init_inputs(8)
    funs = [bf.AND, bf.OR, bf.XOR, bf.A_AND_NOT_B]
    while st.num_gates < gates:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(funs[rng.integers(len(funs))], int(a), int(b), GATES)
    outer = tt.eval_lut(PLANT7_OUTER, st.table(9), st.table(12), st.table(17))
    middle = tt.eval_lut(PLANT7_MIDDLE, st.table(10), st.table(15), st.table(21))
    target = tt.eval_lut(PLANT7_INNER, outer, middle, st.table(19))
    return st, target, tt.mask_table(8)


def verify_lut7_result(st, target, mask, res) -> bool:
    """True iff res = {func_outer, func_middle, func_inner, gates(7)}
    realizes the target."""
    gs = [int(g) for g in res["gates"]]
    o = tt.eval_lut(int(res["func_outer"]), st.table(gs[0]), st.table(gs[1]), st.table(gs[2]))
    m = tt.eval_lut(int(res["func_middle"]), st.table(gs[3]), st.table(gs[4]), st.table(gs[5]))
    got = tt.eval_lut(int(res["func_inner"]), o, m, st.table(gs[6]))
    return bool(tt.eq_mask(got, target, mask))


def build_round_chain(n_rounds=10, gates0=12, seed=7, deep_last=False):
    """(start state, [(target, mask), ...]) for the fused multi-round
    driver tests: each target is one 3-LUT over the SIMULATED evolving
    state (operands sorted BEFORE building the target, so the planted
    table matches the simulated append for non-symmetric functions too).
    ``deep_last`` appends a FINAL target needing a 3-level LUT tree
    (7 distinct leaves) the round kernel cannot finish — the
    host-fallback path.  Last only: the fallback recursion's gate
    choices are its own, so no later planted target may depend on them.
    bench.py's ``_round_chain_problem`` mirrors this construction (bench
    must not import from tests/)."""
    rng = np.random.default_rng(seed)
    st = State.init_inputs(8)
    while st.num_gates < gates0:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    mask = tt.mask_table(8)
    sim = st.copy()
    rounds = []
    for _ in range(n_rounds):
        a, b, c = sorted(
            int(x) for x in rng.choice(sim.num_gates, size=3, replace=False)
        )
        func = int(rng.integers(1, 255))
        rounds.append(
            (tt.eval_lut(func, sim.table(a), sim.table(b), sim.table(c)), mask)
        )
        sim.add_lut(func, a, b, c)
    if deep_last:
        gs = rng.choice(sim.num_gates, size=7, replace=False)
        o = tt.eval_lut(
            0x96, sim.table(int(gs[0])), sim.table(int(gs[1])),
            sim.table(int(gs[2])),
        )
        m = tt.eval_lut(
            0xE8, sim.table(int(gs[3])), sim.table(int(gs[4])),
            sim.table(int(gs[5])),
        )
        rounds.append((tt.eval_lut(0xCA, o, m, sim.table(int(gs[6]))), mask))
    return st, rounds


def verify_lut5_result(st, target, mask, res) -> bool:
    """True iff res = {func_outer, func_inner, gates} realizes the target."""
    a, b, c, d, e = (int(g) for g in res["gates"])
    got = tt.eval_lut(
        int(res["func_inner"]),
        tt.eval_lut(int(res["func_outer"]), st.table(a), st.table(b), st.table(c)),
        st.table(d),
        st.table(e),
    )
    return bool(tt.eq_mask(got, target, mask))
