"""Shared fixture: a 50-gate state with a planted 5-LUT decomposition.

Used by the sharded-pivot equivalence test, the 2-process distributed test,
and its worker subprocess — one construction so the cross-process
verification can never drift out of sync with what the worker searched.
"""

import numpy as np

from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import GATES, State

PLANT_OUTER = 0x2D
PLANT_INNER = 0xB4
PLANT_OUTER_GATES = (12, 26, 41)
PLANT_INNER_GATES = (19, 33)


def build_planted_lut5():
    """(state, target, mask): 8 inputs + XOR gates up to 50 total, with a
    target realizable as LUT(LUT(g12,g26,g41), g19, g33) — large enough that
    C(50,5) crosses the pivot-path threshold."""
    rng = np.random.default_rng(5)
    st = State.init_inputs(8)
    while st.num_gates < 50:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    a, b, c = PLANT_OUTER_GATES
    d, e = PLANT_INNER_GATES
    outer = tt.eval_lut(PLANT_OUTER, st.table(a), st.table(b), st.table(c))
    target = tt.eval_lut(PLANT_INNER, outer, st.table(d), st.table(e))
    return st, target, tt.mask_table(8)


def verify_lut5_result(st, target, mask, res) -> bool:
    """True iff res = {func_outer, func_inner, gates} realizes the target."""
    a, b, c, d, e = (int(g) for g in res["gates"])
    got = tt.eval_lut(
        int(res["func_inner"]),
        tt.eval_lut(int(res["func_outer"]), st.table(a), st.table(b), st.table(c)),
        st.table(d),
        st.table(e),
    )
    return bool(tt.eq_mask(got, target, mask))
