"""Property tests for truth-table primitives against a brute-force oracle."""

import numpy as np
import pytest

from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.core import ttable as tt


def test_pack_roundtrip(rng):
    bits = rng.integers(0, 2, size=(5, 256)).astype(bool)
    assert np.array_equal(tt.to_bits(tt.from_bits(bits)), bits)


def test_input_table_bits():
    for var in range(8):
        bits = tt.to_bits(tt.input_table(var))
        expected = ((np.arange(256) >> var) & 1).astype(bool)
        assert np.array_equal(bits, expected)


def test_target_table_matches_sbox_eval(aes_sbox):
    for bit in range(8):
        bits = tt.to_bits(tt.target_table(aes_sbox, bit))
        expected = ((aes_sbox.astype(np.uint32) >> bit) & 1).astype(bool)
        assert np.array_equal(bits, expected)


def test_mask_table():
    for n in range(1, 9):
        bits = tt.to_bits(tt.mask_table(n))
        assert bits[: 1 << n].all()
        assert not bits[1 << n :].any()


def test_eq_mask(rng):
    a = tt.from_bits(rng.integers(0, 2, 256).astype(bool))
    b = a.copy()
    mask = tt.mask_table(6)
    assert bool(tt.eq_mask(a, b, mask))
    # flip a bit outside the mask: still equal under mask
    b2 = b.copy()
    b2[7] ^= np.uint32(1)
    assert bool(tt.eq_mask(a, b2, mask))
    # flip a bit inside the mask
    b3 = b.copy()
    b3[0] ^= np.uint32(1)
    assert not bool(tt.eq_mask(a, b3, mask))


def test_eq_mask_batched(rng):
    batch = tt.from_bits(rng.integers(0, 2, size=(10, 256)).astype(bool))
    target = batch[3]
    mask = tt.mask_table(8)
    eq = tt.eq_mask(batch, target, mask)
    assert eq.shape == (10,)
    assert eq[3]


def test_eval_gate2_all_functions():
    """Every 2-input function value matches its defining bit layout:
    f(1,1)=bit0, f(1,0)=bit1, f(0,1)=bit2, f(0,0)=bit3."""
    a = tt.input_table(0)
    b = tt.input_table(1)
    abits = tt.to_bits(a)
    bbits = tt.to_bits(b)
    for fun in range(16):
        got = tt.to_bits(tt.eval_gate2(fun, a, b))
        expected = np.array(
            [bf.get_val(fun, int(x), int(y)) for x, y in zip(abits, bbits)],
            dtype=bool,
        )
        assert np.array_equal(got, expected), f"fun={fun}"


def test_eval_gate2_named_gates(rng):
    a = tt.from_bits(rng.integers(0, 2, 256).astype(bool))
    b = tt.from_bits(rng.integers(0, 2, 256).astype(bool))
    assert np.array_equal(tt.eval_gate2(bf.AND, a, b), a & b)
    assert np.array_equal(tt.eval_gate2(bf.OR, a, b), a | b)
    assert np.array_equal(tt.eval_gate2(bf.XOR, a, b), a ^ b)
    assert np.array_equal(tt.eval_gate2(bf.NAND, a, b), ~(a & b))
    assert np.array_equal(tt.eval_gate2(bf.NOR, a, b), ~(a | b))
    assert np.array_equal(tt.eval_gate2(bf.XNOR, a, b), ~(a ^ b))
    assert np.array_equal(tt.eval_gate2(bf.A, a, b), a)
    assert np.array_equal(tt.eval_gate2(bf.B, a, b), b)
    assert np.array_equal(tt.eval_gate2(bf.FALSE_GATE, a, b), tt.zero())
    assert np.array_equal(tt.eval_gate2(bf.TRUE_GATE, a, b), tt.ones())
    assert np.array_equal(tt.eval_gate2(bf.A_AND_NOT_B, a, b), a & ~b)


def test_eval_gate2_vectorized_funs(rng):
    """fun may be an array: one output table per function."""
    a = tt.from_bits(rng.integers(0, 2, 256).astype(bool))
    b = tt.from_bits(rng.integers(0, 2, 256).astype(bool))
    funs = np.arange(16, dtype=np.uint32)[:, None]  # [16, 1] broadcasts over words
    batch = tt.eval_gate2(funs, a, b)
    assert batch.shape == (16, 8)
    for f in range(16):
        assert np.array_equal(batch[f], tt.eval_gate2(f, a, b))


def test_eval_lut_oracle(rng):
    a = tt.from_bits(rng.integers(0, 2, 256).astype(bool))
    b = tt.from_bits(rng.integers(0, 2, 256).astype(bool))
    c = tt.from_bits(rng.integers(0, 2, 256).astype(bool))
    abits, bbits, cbits = tt.to_bits(a), tt.to_bits(b), tt.to_bits(c)
    for func in rng.integers(0, 256, size=32):
        func = int(func)
        got = tt.to_bits(tt.eval_lut(func, a, b, c))
        idx = (abits.astype(int) << 2) | (bbits.astype(int) << 1) | cbits.astype(int)
        expected = ((func >> idx) & 1).astype(bool)
        assert np.array_equal(got, expected)


def test_eval_lut_mux():
    """LUT function 0xac is the multiplexer sel ? c : b used by the
    reference's LUT mux construction (sboxgates.c:506-508)."""
    sel = tt.input_table(0)
    b = tt.input_table(1)
    c = tt.input_table(2)
    got = tt.eval_lut(0xAC, sel, b, c)
    expected = (sel & c) | (~sel & b)
    assert np.array_equal(got, expected)


def test_jnp_compat():
    """The same functions run on jax arrays inside jit."""
    import jax
    import jax.numpy as jnp

    a = jnp.asarray(tt.input_table(0))
    b = jnp.asarray(tt.input_table(1))
    out = jax.jit(lambda x, y: tt.eval_gate2(bf.XOR, x, y))(a, b)
    assert np.array_equal(np.asarray(out), tt.input_table(0) ^ tt.input_table(1))
    eq = jax.jit(lambda x, y: tt.eq_mask(x, y, jnp.asarray(tt.mask_table(8))))(a, a)
    assert bool(eq)


def test_ttable_text_matches_reference_format():
    """ttable_text = the reference's print_ttable byte format
    (convert_graph.c:28-45): 16x16 grid of bits, position 0 first."""
    t = np.zeros(8, dtype=np.uint32)
    t[0] = 0b1011  # positions 0,1,3
    t[2] = 1 << 5  # position 64+5 = 69
    s = tt.ttable_text(t)
    rows = s.splitlines()
    assert len(rows) == 16 and all(len(r) == 16 for r in rows)
    assert s.endswith("\n")
    flat = "".join(rows)
    assert [i for i, c in enumerate(flat) if c == "1"] == [0, 1, 3, 69]
