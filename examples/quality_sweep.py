"""Seed-swept best gate counts: the quality table.

Widens the round-4 quality showcase (17-gate DES S1 bit 0 vs the
reference README's 19-gate des_s1_bit0.svg, reference README.md:33-34)
from one data point to a table: for each target, sweep N seeds of the
randomized search under the showcase's gate family
(avail_gates_bitfield=214 — AND, both ANDNOT forms, XOR, OR) with a
ratcheting gate budget, and commit the best circuit found.  Rows cover
DES S1 outputs 0-3 and the crypto1 filters in gate mode, DES S2-S8
bit 0 in gate mode, and all eight DES boxes' bit 0 in LUT mode
(3-input LUT graphs; rows carry lut_mode=true).  In every mode,
`best_gates` counts ALL non-input nodes: for LUT-mode rows that is the
3-LUTs plus any NOT/2-input helper gates the search reused (the allowed
set test_quality checks), NOT a pure-LUT count.

Each row is deterministically reproducible: `best_seed` under a
`max_gates` budget of (best+1 extra node) re-derives `best_gates` —
that's what tests/test_quality.py asserts for every committed artifact.

Usage:  JAX_PLATFORMS=cpu python examples/quality_sweep.py [seeds]
Writes examples/quality_table.json and examples/<target>_best.xml.

The des_s1_bit0 row canonicalizes to the round-4 showcase artifact
(des_s1_bit0_17gates.xml) when the sweep re-derives the identical
circuit — no duplicate file, and a regenerated table keeps pointing at
the committed artifact.
"""

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Pin the CPU backend the way conftest.py/bench.py do: the axon
# sitecustomize re-forces the tunnel platform at interpreter start, so
# the env var alone is not reliable — set both before the package
# (and so jax) initializes a backend.  A dead tunnel otherwise hangs
# the first dispatch forever.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from sboxgates_tpu.core import ttable as tt  # noqa: E402
from sboxgates_tpu.graph.state import NO_GATE, State  # noqa: E402
from sboxgates_tpu.graph import xmlio  # noqa: E402
from sboxgates_tpu.search import Options, SearchContext  # noqa: E402
from sboxgates_tpu.search.kwan import create_circuit  # noqa: E402
from sboxgates_tpu.utils.sbox import load_sbox  # noqa: E402

GATE_FAMILY = 214  # the showcase family: AND | ANDNOT both | XOR | OR
INITIAL_EXTRA = 18  # first-seed budget: inputs + 18 candidate nodes
# (the round-4 showcase swept at max_gates = 24 total for the 6-input
# target; larger first budgets make failing seeds exponentially slow)
INITIAL_EXTRA_LUT = 12  # LUT graphs are ~2x denser (a 3-LUT subsumes
# several 2-input gates), so the tight first budget is lower

# Rows whose circuit may already exist under a committed canonical
# name (see the module docstring's curation note).
CANONICAL_ARTIFACTS = {"des_s1_bit0": "des_s1_bit0_17gates.xml"}

# (label, sbox file, output bit, lut_mode)
TARGETS = [
    ("des_s1_bit0", "des_s1.txt", 0, False),
    ("des_s1_bit1", "des_s1.txt", 1, False),
    ("des_s1_bit2", "des_s1.txt", 2, False),
    ("des_s1_bit3", "des_s1.txt", 3, False),
    ("crypto1_fa", "crypto1_fa.txt", 0, False),
    ("crypto1_fb", "crypto1_fb.txt", 0, False),
    ("crypto1_fc", "crypto1_fc.txt", 0, False),
] + [
    (f"des_s{i}_bit0", f"des_s{i}.txt", 0, False) for i in range(2, 9)
] + [
    # LUT-mode rows (3-input LUT graphs, the reference front page's own
    # headline mode for AES).  best_gates still counts every non-input
    # node — 3-LUTs plus reused NOT/2-input gates — not pure LUTs.
    (f"des_s{i}_bit0_lut", f"des_s{i}.txt", 0, True) for i in range(1, 9)
] + [
    ("crypto1_fa_lut", "crypto1_fa.txt", 0, True),
    ("crypto1_fb_lut", "crypto1_fb.txt", 0, True),
    ("crypto1_fc_lut", "crypto1_fc.txt", 0, True),
]


def sweep_target(label, sbox_file, bit, seeds, lut_mode=False):
    sbox, n = load_sbox(os.path.join(REPO, "sboxes", sbox_file))
    target = np.asarray(tt.target_table(sbox, bit))
    mask = np.asarray(tt.mask_table(n))
    best = None  # (gates, seed, budget_at_best, state)
    budget = n + (INITIAL_EXTRA_LUT if lut_mode else INITIAL_EXTRA)
    while best is None:
        for seed in range(seeds):
            st = State.init_inputs(n)
            st.max_gates = budget
            ctx = SearchContext(
                Options(seed=seed, avail_gates_bitfield=GATE_FAMILY,
                        lut_graph=lut_mode)
            )
            out = create_circuit(ctx, st, target, mask, [])
            if out == NO_GATE:
                continue
            got = np.asarray(st.tables[out])
            assert np.array_equal(got & mask, target & mask), (label, seed)
            st.outputs[bit] = out
            gates = st.num_gates - st.num_inputs
            if best is None or gates < best[0]:
                # budget is what this seed's search actually ran under —
                # recorded so the row is deterministically reproducible
                # (seed + budget + family re-derive the circuit).
                best = (gates, seed, budget, st.copy())
                # Ratchet: later seeds must strictly improve, so their
                # searches prune at the new bound and the sweep stays
                # fast.
                budget = st.num_gates - 1
        if best is None:
            # The target's minimum exceeds the tight initial budget:
            # widen and re-sweep (slow, but only for hard targets).
            budget += 4
            assert budget <= n + 40, f"{label}: no circuit by budget 40"
            print(f"{label}: widening budget to {budget}", flush=True)
    return best


def main():
    seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 400
    # Optional label filter (argv[2:]): sweep only the named targets and
    # MERGE their rows into the existing table, so extending the table
    # never re-runs (or clobbers) the committed rows.
    only = set(sys.argv[2:])
    known = {t[0] for t in TARGETS}
    if not only <= known:
        sys.exit(f"unknown target labels: {sorted(only - known)}; "
                 f"known: {sorted(known)}")
    table_path = os.path.join(REPO, "examples", "quality_table.json")
    table = []
    if only and os.path.exists(table_path):
        with open(table_path) as f:
            table = [r for r in json.load(f) if r["target"] not in only]
    targets = [t for t in TARGETS if not only or t[0] in only]
    for label, sbox_file, bit, lut_mode in targets:
        gates, seed, budget, st = sweep_target(
            label, sbox_file, bit, seeds, lut_mode
        )
        xml = xmlio.state_to_xml(st)
        path = os.path.join(REPO, "examples", f"{label}_best.xml")
        # Canonicalize onto an already-committed identical artifact
        # (e.g. the round-4 bit-0 showcase) so regeneration never
        # produces a duplicate file or re-points the table away from
        # the committed name.
        canonical = CANONICAL_ARTIFACTS.get(label)
        if canonical is not None:
            cpath = os.path.join(REPO, "examples", canonical)
            if os.path.exists(cpath) and open(cpath).read() == xml:
                path = cpath
        if path.endswith(f"{label}_best.xml"):
            with open(path, "w") as f:
                f.write(xml)
        table.append(
            {"target": label, "sbox": sbox_file, "bit": bit,
             "best_gates": gates, "best_seed": seed, "budget": budget,
             "gate_family": GATE_FAMILY, "seeds_swept": seeds,
             "lut_mode": lut_mode,
             "artifact": os.path.basename(path)}
        )
        print(
            f"{label}: {gates} gates (seed {seed}, budget {budget})",
            flush=True,
        )
    order = {t[0]: i for i, t in enumerate(TARGETS)}
    table.sort(key=lambda r: order.get(r["target"], len(order)))
    with open(table_path, "w") as f:
        json.dump(table, f, indent=1)


if __name__ == "__main__":
    main()
